"""Flash attention + ring context parallelism tests (§5.7 mandate).

The Pallas kernel runs in interpreter mode on the CPU test mesh; the
ring runs over the 8-device shard_map mesh — both are checked against
the fp32 reference math.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.flash_attention import (_naive_attention,
                                           flash_attention)
from mxnet_tpu.parallel import get_mesh
from mxnet_tpu.parallel import ring as ring_mod

onp.random.seed(13)


def _qkv(b=2, h=2, s=256, d=64, dtype="float32"):
    q = onp.random.randn(b, h, s, d).astype(dtype) * 0.3
    k = onp.random.randn(b, h, s, d).astype(dtype) * 0.3
    v = onp.random.randn(b, h, s, d).astype(dtype) * 0.3
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_kernel_matches_naive(causal):
    q, k, v = _qkv()
    ref = _naive_attention(q, k, v, causal, 1.0 / 8.0)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)


def test_flash_kernel_bf16():
    q, k, v = _qkv(s=128)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb, interpret=True)
    ref = _naive_attention(q, k, v, False, 1.0 / 8.0)
    assert out.dtype == jnp.bfloat16
    onp.testing.assert_allclose(onp.asarray(out, dtype="float32"),
                                onp.asarray(ref), rtol=5e-2, atol=5e-2)


def test_flash_gradient_matches_naive():
    q, k, v = _qkv(b=1, h=1, s=128, d=64)

    def loss_flash(q_, k_, v_):
        return (flash_attention(q_, k_, v_, causal=True,
                                interpret=True) ** 2).sum()

    def loss_naive(q_, k_, v_):
        return (_naive_attention(q_, k_, v_, True, 1.0 / 8.0) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gn):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                    rtol=1e-3, atol=1e-4)


def test_flash_fallback_odd_shapes():
    # 100 % 128 != 0 -> naive fallback, still correct
    q, k, v = _qkv(s=100)
    out = flash_attention(q, k, v)
    ref = _naive_attention(q, k, v, False, 1.0 / 8.0)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)


def test_dot_product_attention_op():
    b, s, nh, d = 2, 64, 4, 16
    q = mx.nd.array(onp.random.randn(b, s, nh * d).astype("float32"))
    k = mx.nd.array(onp.random.randn(b, s, nh * d).astype("float32"))
    v = mx.nd.array(onp.random.randn(b, s, nh * d).astype("float32"))
    out = mx.nd.invoke("_contrib_dot_product_attention", [q, k, v],
                       num_heads=nh)
    assert out.shape == (b, s, nh * d)
    # gradient flows through the custom vjp
    q.attach_grad()
    from mxnet_tpu import autograd

    with autograd.record():
        o = mx.nd.invoke("_contrib_dot_product_attention", [q, k, v],
                         num_heads=nh)
        loss = (o * o).sum()
    loss.backward()
    assert onp.abs(q.grad.asnumpy()).max() > 0


def test_div_sqrt_dim():
    x = mx.nd.ones((2, 16))
    out = mx.nd.invoke("_contrib_div_sqrt_dim", [x])
    onp.testing.assert_allclose(out.asnumpy(), onp.ones((2, 16)) / 4.0)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring CP over the 8-device mesh == full attention (SURVEY.md
    §5.7: 'correctness test vs naive attention on the CPU mesh')."""
    mesh = get_mesh((8,), ("seq",))
    b, h, s, d = 2, 2, 128, 32  # 16 tokens per device
    q, k, v = _qkv(b, h, s, d)
    out = ring_mod.ring_attention(q, k, v, mesh, axis_name="seq",
                                  causal=causal)
    ref = _naive_attention(q, k, v, causal, 1.0 / (d ** 0.5))
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=2e-4, atol=2e-5)


def test_ring_attention_memory_contract():
    """Each device's shard is seq/n — the point of the ring."""
    mesh = get_mesh((8,), ("seq",))
    q, k, v = _qkv(1, 1, 64, 16)
    out = ring_mod.ring_attention(q, k, v, mesh)
    shard_shapes = {tuple(s.data.shape)
                    for s in out.addressable_shards}
    assert shard_shapes == {(1, 1, 8, 16)}


def test_ring_attention_gradients():
    mesh = get_mesh((8,), ("seq",))
    b, h, s, d = 1, 1, 64, 16
    q, k, v = _qkv(b, h, s, d)

    def loss_ring(q_, k_, v_):
        return (ring_mod.ring_attention(q_, k_, v_, mesh) ** 2).sum()

    def loss_naive(q_, k_, v_):
        return (_naive_attention(q_, k_, v_, False,
                                 1.0 / (d ** 0.5)) ** 2).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gn):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b_),
                                    rtol=1e-3, atol=1e-4)


# ---------------------------------------- round 14: variants + pad shim
@pytest.mark.parametrize("causal", [False, True])
def test_pallas_pad_variant_matches_naive_nonaligned(causal):
    """The padding shim: non-tile-aligned, NON-SQUARE seq lens run the
    kernel padded with masked keys; fwd and bwd match the reference
    (bottom-right causal alignment computed against the VALID key
    length, not the padded one)."""
    rng = onp.random.RandomState(3)
    q = jnp.asarray(rng.randn(2, 2, 70, 16).astype("float32") * 0.3)
    k = jnp.asarray(rng.randn(2, 2, 90, 16).astype("float32") * 0.3)
    v = jnp.asarray(rng.randn(2, 2, 90, 16).astype("float32") * 0.3)
    ref = _naive_attention(q, k, v, causal, 0.25)
    out = flash_attention(q, k, v, causal=causal, sm_scale=0.25,
                          variant="pallas_pad", interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)

    def loss_pad(q_, k_, v_):
        return (flash_attention(q_, k_, v_, causal=causal,
                                sm_scale=0.25, variant="pallas_pad",
                                interpret=True) ** 2).sum()

    def loss_naive(q_, k_, v_):
        return (_naive_attention(q_, k_, v_, causal, 0.25) ** 2).sum()

    gp = jax.grad(loss_pad, argnums=(0, 1, 2))(q, k, v)
    gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gp, gn):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b_),
                                    rtol=1e-4, atol=1e-5)


def test_block_size_subvariant_matches_naive():
    q, k, v = _qkv(b=1, h=2, s=256, d=16)
    ref = _naive_attention(q, k, v, True, 0.25)
    out = flash_attention(q, k, v, causal=True, sm_scale=0.25,
                          variant="pallas_b256", interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)


def test_variant_registry_consult(tmp_path, monkeypatch):
    """flash_attention with no explicit variant consults the autotune
    registry: a force scope pins the lowering, and a cached winner
    applies through program_scope."""
    from mxnet_tpu import autotune as at

    monkeypatch.setenv("MXNET_AUTOTUNE_CACHE_DIR",
                       str(tmp_path / "atc"))
    at.cache_clear()
    q, k, v = _qkv(b=1, h=1, s=64, d=8)
    ref = _naive_attention(q, k, v, False, 1.0 / (8 ** 0.5))
    with at.force(flash_attention="pallas_pad"):
        out = flash_attention(q, k, v, interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)
    # cached winner path: record + program_scope -> same answer
    at.record("flash_attention", tuple(q.shape), "float32",
              winner="naive", platform="cpu", mesh="none")
    with at.program_scope(q.shape, "float32", platform="cpu",
                          mesh="none"):
        out2 = flash_attention(q, k, v)
    onp.testing.assert_allclose(onp.asarray(out2), onp.asarray(ref),
                                rtol=1e-6, atol=1e-7)
    at.cache_clear()


def test_fallback_emits_autotune_event(tmp_path):
    """_can_use_pallas' silent fallback is gone: a non-tile-aligned
    shape that consulted the default heuristic leaves an ``autotune``
    event naming the reason in the armed run log."""
    import json

    from mxnet_tpu import telemetry

    path = str(tmp_path / "run.jsonl")
    rl = telemetry.reset(path)
    try:
        q, k, v = _qkv(b=1, h=1, s=100, d=8)
        _ = flash_attention(q, k, v)  # 100 % 128 -> fallback
    finally:
        telemetry.close()
    events = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("type") == "event" and \
                    rec.get("kind") == "autotune":
                events.append(rec)
    assert events, "fallback must leave an attributed autotune event"
    ev = events[-1]
    assert ev["op"] == "flash_attention"
    assert ev["winner"] == "naive"
    assert "tile-aligned" in ev["reason"]
    assert "pallas_pad" in ev["reason"]


# ------------------------------------- round 17: ragged-tail exactness
_ALL_VARIANTS = ("naive", "pallas", "pallas_b256", "pallas_pad")


@pytest.mark.parametrize("variant", _ALL_VARIANTS)
@pytest.mark.parametrize("causal", [False, True])
def test_ragged_tail_matches_reference_all_variants(causal, variant):
    """Every registered flash_attention variant agrees with the fp32
    reference on a RAGGED prompt shape (the generative prefill case:
    s=10 inside a padded bucket).  Forced kernel variants that cannot
    tile fall back to naive — the answer must still be exact."""
    from mxnet_tpu.autotune import VARIANT_OPS

    assert set(_ALL_VARIANTS) == set(VARIANT_OPS["flash_attention"]), \
        "a new registered variant must join this exactness matrix"
    rng = onp.random.RandomState(17)
    q = jnp.asarray(rng.randn(1, 2, 10, 8).astype("float32") * 0.3)
    k = jnp.asarray(rng.randn(1, 2, 10, 8).astype("float32") * 0.3)
    v = jnp.asarray(rng.randn(1, 2, 10, 8).astype("float32") * 0.3)
    ref = _naive_attention(q, k, v, causal, 8 ** -0.5)
    out = flash_attention(q, k, v, causal=causal, variant=variant,
                          interpret=True)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("variant", _ALL_VARIANTS)
@pytest.mark.parametrize("causal", [False, True])
def test_padded_rows_contribute_exactly_zero(causal, variant):
    """The padding-mask proof: blocks-aligned inputs whose tail keys
    hold 1e9 GARBAGE must reproduce the valid-slice reference — any
    nonzero softmax mass on a padded row would swamp the output by
    ~1e9, so agreement at 1e-5 means the tail's normalization weight
    is exactly zero in every variant."""
    from mxnet_tpu.ops.flash_attention import _flash

    rng = onp.random.RandomState(23)
    valid = 10
    q = jnp.asarray(rng.randn(1, 2, valid, 8).astype("float32") * 0.3)
    k = jnp.asarray(rng.randn(1, 2, valid, 8).astype("float32") * 0.3)
    v = jnp.asarray(rng.randn(1, 2, valid, 8).astype("float32") * 0.3)
    ref = _naive_attention(q, k, v, causal, 8 ** -0.5)
    pad = 128 - valid
    widths = ((0, 0), (0, 0), (0, pad), (0, 0))
    qp = jnp.pad(q, widths)
    kp = jnp.pad(k, widths, constant_values=1e9)
    vp = jnp.pad(v, widths, constant_values=1e9)
    out = _flash(qp, kp, vp, causal, 8 ** -0.5, True, variant,
                 valid, valid)
    got = onp.asarray(out[:, :, :valid, :])
    assert onp.isfinite(got).all(), \
        f"{variant}: padded garbage leaked into the output"
    onp.testing.assert_allclose(got, onp.asarray(ref),
                                rtol=1e-5, atol=1e-6)
