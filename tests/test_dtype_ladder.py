"""f64/f32/bf16 consistency ladder for the nn op family.

VERDICT r03 weak #8: ``check_consistency`` (reference test_utils.py
:1259 — there the axis is cpu-vs-gpu, here it is the dtype ladder:
one XLA program serves every backend) was exercised only sporadically.
This sweeps the core nn family: each op runs in float64, float32 and
bfloat16 on identical inputs, and every narrower result must match the
float64 reference within that dtype's tolerance.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym

TOLS = {"float32": (1e-4, 1e-5), "bfloat16": (5e-2, 5e-2)}


def _ladder(build, arg_shapes, scale=1.0, aux_ones=()):
    """Run the symbol across the dtype ladder and compare to f64."""
    from mxnet_tpu.test_utils import enable_x64 as _enable_x64

    rng = onp.random.RandomState(0)
    s = build()
    args64 = {}
    for name, shape in arg_shapes.items():
        args64[name] = rng.normal(scale=scale, size=shape)
    outs = {}
    # x64 must be live or the float64 rung silently truncates to f32
    # and the ladder compares f32 against itself
    with _enable_x64():
        for dtype in ("float64", "float32", "bfloat16"):
            args = {k: mx.nd.array(v.astype("float32")).astype(dtype)
                    for k, v in args64.items()}
            aux = {n: mx.nd.ones(shape).astype(dtype)
                   for n, shape in aux_ones}
            ex = s.bind(mx.cpu(), args=args, aux_states=aux or None)
            out = ex.forward()[0]
            assert str(out.dtype) == dtype, (
                f"{build.__name__}: output dtype {out.dtype} != input "
                f"rung {dtype}")
            outs[dtype] = out.asnumpy().astype("float64")
    for dtype, (rtol, atol) in TOLS.items():
        onp.testing.assert_allclose(
            outs[dtype], outs["float64"], rtol=rtol, atol=atol,
            err_msg=f"{build.__name__} diverges at {dtype}")


def test_convolution_ladder():
    def conv():
        return sym.Convolution(sym.Variable("data"),
                               sym.Variable("w"), sym.Variable("b"),
                               kernel=(3, 3), num_filter=8, pad=(1, 1),
                               name="c")
    _ladder(conv, {"data": (2, 4, 12, 12), "w": (8, 4, 3, 3),
                   "b": (8,)}, scale=0.5)


def test_fully_connected_ladder():
    def fc():
        return sym.FullyConnected(sym.Variable("data"),
                                  sym.Variable("w"), sym.Variable("b"),
                                  num_hidden=16, name="f")
    _ladder(fc, {"data": (8, 24), "w": (16, 24), "b": (16,)}, scale=0.5)


def test_batchnorm_ladder():
    def bn():
        return sym.BatchNorm(sym.Variable("data"), name="bn0",
                             fix_gamma=False)
    _ladder(bn, {"data": (4, 6, 8, 8), "bn0_gamma": (6,),
                 "bn0_beta": (6,)},
            aux_ones=(("bn0_moving_mean", (6,)),
                      ("bn0_moving_var", (6,))))


def test_layernorm_ladder():
    def ln():
        return sym.LayerNorm(sym.Variable("data"), sym.Variable("g"),
                             sym.Variable("b"), name="ln")
    _ladder(ln, {"data": (6, 32), "g": (32,), "b": (32,)})


def test_pooling_ladder():
    def pool():
        return sym.Pooling(sym.Variable("data"), kernel=(2, 2),
                           stride=(2, 2), pool_type="avg", name="p")
    _ladder(pool, {"data": (2, 4, 8, 8)})


def test_softmax_ladder():
    def sm():
        return sym.softmax(sym.Variable("data"), name="s")
    _ladder(sm, {"data": (8, 32)})


def test_activation_ladder():
    def act():
        return sym.Activation(sym.Variable("data"), act_type="tanh",
                              name="a")
    _ladder(act, {"data": (8, 32)})


def test_deconvolution_ladder():
    def deconv():
        return sym.Deconvolution(sym.Variable("data"),
                                 sym.Variable("w"), kernel=(3, 3),
                                 num_filter=4, stride=(2, 2),
                                 pad=(1, 1), no_bias=True, name="d")
    _ladder(deconv, {"data": (2, 6, 8, 8), "w": (6, 4, 3, 3)},
            scale=0.5)
