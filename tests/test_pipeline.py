"""Pipeline parallelism (parallel.pipeline): GPipe schedule over shard_map.

Reference analog: MXNet's model parallelism is manual device placement
(example/model-parallel); the TPU rebuild makes pipeline a mesh axis.
These run on the 8-device virtual CPU mesh (conftest.py).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.parallel import get_mesh
from mxnet_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)

N_STAGES = 4
D = 16


def _make_stages(key, n=N_STAGES, d=D):
    stages = []
    for _ in range(n):
        k1, k2, key = jax.random.split(key, 3)
        stages.append({"w": jax.random.normal(k1, (d, d)) * 0.3,
                       "b": jax.random.normal(k2, (d,)) * 0.1})
    return stages


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _seq_apply(stages, x):
    for s in stages:
        x = _stage_fn(s, x)
    return x


@pytest.fixture(scope="module")
def mesh():
    return get_mesh((N_STAGES,), ("pipe",),
                    devices=jax.devices()[:N_STAGES])


def test_pipeline_matches_sequential(mesh):
    key = jax.random.PRNGKey(0)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, D))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, n_microbatches=8)
    ref = _seq_apply(stages, x)
    assert onp.allclose(onp.asarray(out), onp.asarray(ref), atol=1e-5)


def test_pipeline_microbatch_counts(mesh):
    key = jax.random.PRNGKey(2)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (24, D))
    ref = _seq_apply(stages, x)
    for m in (4, 6, 12, 24):
        out = pipeline_apply(_stage_fn, stacked, x, mesh,
                             n_microbatches=m)
        assert onp.allclose(onp.asarray(out), onp.asarray(ref),
                            atol=1e-5), m


def test_pipeline_is_differentiable(mesh):
    key = jax.random.PRNGKey(4)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, D))

    def loss(st):
        return (pipeline_apply(_stage_fn, st, x, mesh,
                               n_microbatches=8) ** 2).sum()

    def loss_ref(st):
        r = x
        for i in range(N_STAGES):
            r = _stage_fn(
                jax.tree_util.tree_map(lambda a: a[i], st), r)
        return (r ** 2).sum()

    g = jax.grad(loss)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for name in g:
        assert onp.allclose(onp.asarray(g[name]),
                            onp.asarray(g_ref[name]), atol=1e-4), name


def test_pipeline_validates_shapes(mesh):
    stages = _make_stages(jax.random.PRNGKey(6), n=3)  # wrong count
    stacked = stack_stage_params(stages)
    x = jnp.zeros((8, D))
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, stacked, x, mesh)
