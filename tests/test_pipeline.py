"""Pipeline parallelism (parallel.pipeline): GPipe schedule over shard_map.

Reference analog: MXNet's model parallelism is manual device placement
(example/model-parallel); the TPU rebuild makes pipeline a mesh axis.
These run on the 8-device virtual CPU mesh (conftest.py).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

from mxnet_tpu.parallel import get_mesh
from mxnet_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params)

N_STAGES = 4
D = 16


def _make_stages(key, n=N_STAGES, d=D):
    stages = []
    for _ in range(n):
        k1, k2, key = jax.random.split(key, 3)
        stages.append({"w": jax.random.normal(k1, (d, d)) * 0.3,
                       "b": jax.random.normal(k2, (d,)) * 0.1})
    return stages


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _seq_apply(stages, x):
    for s in stages:
        x = _stage_fn(s, x)
    return x


@pytest.fixture(scope="module")
def mesh():
    return get_mesh((N_STAGES,), ("pipe",),
                    devices=jax.devices()[:N_STAGES])


def test_pipeline_matches_sequential(mesh):
    key = jax.random.PRNGKey(0)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, D))
    out = pipeline_apply(_stage_fn, stacked, x, mesh, n_microbatches=8)
    ref = _seq_apply(stages, x)
    assert onp.allclose(onp.asarray(out), onp.asarray(ref), atol=1e-5)


def test_pipeline_microbatch_counts(mesh):
    key = jax.random.PRNGKey(2)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (24, D))
    ref = _seq_apply(stages, x)
    for m in (4, 6, 12, 24):
        out = pipeline_apply(_stage_fn, stacked, x, mesh,
                             n_microbatches=m)
        assert onp.allclose(onp.asarray(out), onp.asarray(ref),
                            atol=1e-5), m


def test_pipeline_is_differentiable(mesh):
    key = jax.random.PRNGKey(4)
    stages = _make_stages(key)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, D))

    def loss(st):
        return (pipeline_apply(_stage_fn, st, x, mesh,
                               n_microbatches=8) ** 2).sum()

    def loss_ref(st):
        r = x
        for i in range(N_STAGES):
            r = _stage_fn(
                jax.tree_util.tree_map(lambda a: a[i], st), r)
        return (r ** 2).sum()

    g = jax.grad(loss)(stacked)
    g_ref = jax.grad(loss_ref)(stacked)
    for name in g:
        assert onp.allclose(onp.asarray(g[name]),
                            onp.asarray(g_ref[name]), atol=1e-4), name


def test_pipeline_validates_shapes(mesh):
    stages = _make_stages(jax.random.PRNGKey(6), n=3)  # wrong count
    stacked = stack_stage_params(stages)
    x = jnp.zeros((8, D))
    with pytest.raises(ValueError):
        pipeline_apply(_stage_fn, stacked, x, mesh)


# ------------------------------------------------ real-model training
# VERDICT r03 weak #7: PP was only validated on 16-dim toy stages.
# This trains a 4-stage causal-transformer LM (>1M params) through the
# GPipe pipeline and asserts loss parity with plain sequential
# execution at EVERY step.

D_MODEL, N_HEADS, D_FF, SEQ = 128, 4, 1024, 32


def _xf_stage_params(key, d=D_MODEL, ff=D_FF):
    ks = jax.random.split(key, 6)
    s = 1.0 / onp.sqrt(d)
    return {
        "wqkv": jax.random.normal(ks[0], (d, 3 * d)) * s,
        "wo": jax.random.normal(ks[1], (d, d)) * s,
        "w1": jax.random.normal(ks[2], (d, ff)) * s,
        "w2": jax.random.normal(ks[3], (ff, d)) * (1.0 / onp.sqrt(ff)),
        "ln1": jnp.ones((d,)), "ln2": jnp.ones((d,)),
    }


def _ln(x, g):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) * jax.lax.rsqrt(v + 1e-5) * g


def _xf_stage(p, x):
    """One pre-LN causal transformer block, (B, T, D) -> (B, T, D)."""
    b, t, d = x.shape
    h = _ln(x, p["ln1"])
    qkv = h @ p["wqkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    hd = d // N_HEADS
    q = q.reshape(b, t, N_HEADS, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, t, N_HEADS, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, t, N_HEADS, hd).transpose(0, 2, 1, 3)
    att = (q @ k.transpose(0, 1, 3, 2)) / onp.sqrt(hd)
    mask = jnp.tril(jnp.ones((t, t), bool))
    att = jnp.where(mask, att, -1e9)
    att = jax.nn.softmax(att, axis=-1)
    o = (att @ v).transpose(0, 2, 1, 3).reshape(b, t, d)
    x = x + o @ p["wo"]
    h = _ln(x, p["ln2"])
    return x + jax.nn.relu(h @ p["w1"]) @ p["w2"]


def test_pipeline_transformer_lm_training(mesh):
    """>=1M-param 4-stage transformer: 12 SGD steps through the GPipe
    pipeline match sequential execution step-for-step."""
    key = jax.random.PRNGKey(7)
    stages = [_xf_stage_params(k) for k in jax.random.split(key, N_STAGES)]
    stacked = stack_stage_params(stages)
    n_params = sum(leaf.size for leaf in jax.tree_util.tree_leaves(stacked))
    assert n_params > 1_000_000, n_params

    xk, yk = jax.random.split(jax.random.PRNGKey(8))
    x = jax.random.normal(xk, (16, SEQ, D_MODEL)) * 0.5
    target = jax.random.normal(yk, (16, SEQ, D_MODEL)) * 0.5

    def loss_pipe(st):
        out = pipeline_apply(_xf_stage, st, x, mesh, n_microbatches=8)
        return jnp.mean((out - target) ** 2)

    def loss_seq(st):
        r = x
        for i in range(N_STAGES):
            r = _xf_stage(
                jax.tree_util.tree_map(lambda a: a[i], st), r)
        return jnp.mean((r - target) ** 2)

    lr = 0.005
    st_p = stacked
    st_s = jax.tree_util.tree_map(lambda a: a, stacked)
    losses_p, losses_s = [], []
    gp = jax.jit(jax.value_and_grad(loss_pipe))
    gs = jax.jit(jax.value_and_grad(loss_seq))
    for _ in range(12):
        lp, grad_p = gp(st_p)
        ls, grad_s = gs(st_s)
        st_p = jax.tree_util.tree_map(lambda w, g: w - lr * g, st_p,
                                      grad_p)
        st_s = jax.tree_util.tree_map(lambda w, g: w - lr * g, st_s,
                                      grad_s)
        losses_p.append(float(lp))
        losses_s.append(float(ls))
    assert losses_p[-1] < losses_p[0], losses_p  # it actually trains
    onp.testing.assert_allclose(losses_p, losses_s, rtol=2e-4,
                                err_msg="pipeline diverged from "
                                        "sequential execution")
