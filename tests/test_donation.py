"""Buffer donation (static_alloc ≡ donate_argnums, SURVEY §7).

Donated runs must compute the same result as non-donated runs, and the
donated input buffers must actually be consumed (invalidated) — the
whole point is that XLA writes the updated params/opt-state into the
input buffers instead of allocating a second copy.
"""
import numpy as onp

import jax
import jax.numpy as jnp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import make_train_step


def _net(with_bn):
    mx.random.seed(0)
    onp.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        if with_bn:
            net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(),
                    nn.Dense(2))
        else:
            net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    net(mx.nd.zeros((1, 4)))
    return net


def _batch():
    rng = onp.random.RandomState(0)
    x = jnp.asarray(rng.rand(8, 4).astype("float32"))
    y = jnp.asarray((rng.rand(8) > 0.5).astype("float32"))
    return x, y, jax.random.key(0)


def _run_steps(net, donate, steps=3):
    """Run `steps` fused steps; returns (loss, params, input buffers
    of step 1) so callers can assert on donation consumption."""
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step_fn, params, opt_state = make_train_step(
        net, loss_fn, optimizer="sgd", learning_rate=0.5, momentum=0.9,
        donate=donate)
    first_in = jax.tree_util.tree_leaves((params, opt_state))
    x, y, key = _batch()
    loss = None
    for i in range(steps):
        loss, params, opt_state = step_fn(params, opt_state, x, y, key,
                                          float(i + 1))
    return (float(loss), {n: onp.asarray(v) for n, v in params.items()},
            first_in)


def test_donated_step_bit_identical_and_invalidates():
    """donate=True computes the SAME update as donate=False (donation
    is a memory contract, not a numeric one); the donated first-step
    inputs are consumed, the non-donated ones stay live, and the
    Gluon block's own weight buffers survive (the step rematerializes
    fresh buffers to donate)."""
    net = _net(with_bn=False)
    l_ref, p_ref, in_ref = _run_steps(net, donate=False)
    l_don, p_don, in_don = _run_steps(net, donate=True)
    assert l_ref == l_don
    for n in p_ref:
        assert (p_ref[n] == p_don[n]).all(), f"{n} not bit-identical"
    # the donated run CONSUMED its inputs; the plain run did not
    assert all(leaf.is_deleted() for leaf in in_don)
    assert not any(leaf.is_deleted() for leaf in in_ref)
    # the block's own buffers are intact after the donated run
    for p in net.collect_params().values():
        assert onp.isfinite(p.data().asnumpy()).all()


def test_donated_step_bn_matches():
    """With BatchNorm in the net, XLA's fusion order under the aliasing
    annotation may differ in the last ulp (measured ~1e-8 abs on the
    first step, CPU; bit-identity holds for nets without BN — see the
    test above).  One step keeps the comparison at that codegen-noise
    floor instead of letting SGD amplify it."""
    net = _net(with_bn=True)
    l_ref, p_ref, _ = _run_steps(net, donate=False, steps=1)
    l_don, p_don, _ = _run_steps(net, donate=True, steps=1)
    assert abs(l_ref - l_don) < 1e-6
    for n in p_ref:
        onp.testing.assert_allclose(p_ref[n], p_don[n], rtol=1e-4,
                                    atol=1e-6)


def test_cachedop_donation_train_forward():
    """Hybridized train-mode forward (no autograd recording): the
    second call takes the donating twin; BatchNorm moving stats keep
    updating and the outputs stay identical call to call."""
    net = _net(with_bn=True)
    net.hybridize()
    x = mx.nd.array(onp.random.RandomState(1).rand(8, 4)
                    .astype("float32"))
    stats = [p for p in net.collect_params().values()
             if p.name.endswith(("running_mean", "running_var"))]
    assert stats
    with autograd.train_mode():
        o1 = net(x).asnumpy()
        m1 = [s.data().asnumpy().copy() for s in stats]
        o2 = net(x).asnumpy()  # donating path (meta known)
        m2 = [s.data().asnumpy().copy() for s in stats]
        o3 = net(x).asnumpy()
    assert any((a != b).any() for a, b in zip(m1, m2))  # stats moved
    onp.testing.assert_allclose(o1, o2, rtol=1e-6)
    onp.testing.assert_allclose(o2, o3, rtol=1e-6)
    # eval forward after donation: block state is intact
    e = net(x).asnumpy()
    assert onp.isfinite(e).all()


def test_executor_donation_train_direct():
    """Symbol executor, is_train=True with grad_req null (the direct
    jit path): moving stats update every call, forwards are stable,
    and a later eval forward still works."""
    import mxnet_tpu.symbol as sym

    data = sym.var("data")
    out = sym.BatchNorm(data, sym.var("gamma"), sym.var("beta"),
                        sym.var("mm"), sym.var("mv"), name="bn")
    ex = out.bind(
        mx.cpu(),
        args={"data": mx.nd.random_uniform(shape=(4, 3)),
              "gamma": mx.nd.ones((3,)), "beta": mx.nd.zeros((3,))},
        args_grad=None, grad_req="null",
        aux_states={"mm": mx.nd.zeros((3,)), "mv": mx.nd.ones((3,))})
    r1 = ex.forward(is_train=True)[0].asnumpy()
    mm1 = ex.aux_dict["mm"].asnumpy().copy()
    r2 = ex.forward(is_train=True)[0].asnumpy()  # donating from here
    mm2 = ex.aux_dict["mm"].asnumpy().copy()
    r3 = ex.forward(is_train=True)[0].asnumpy()
    assert (mm1 != 0).any() and (mm2 != mm1).any()
    onp.testing.assert_allclose(r1, r2, rtol=1e-6)
    onp.testing.assert_allclose(r2, r3, rtol=1e-6)
    re = ex.forward(is_train=False)[0].asnumpy()
    assert re.shape == (4, 3)


def test_exec_donate_env_disables(monkeypatch):
    """MXNET_EXEC_DONATE=0 keeps the executor paths on the plain
    program (the donating twin is never taken)."""
    monkeypatch.setenv("MXNET_EXEC_DONATE", "0")
    net = _net(with_bn=True)
    net.hybridize()
    x = mx.nd.array(onp.random.RandomState(1).rand(8, 4)
                    .astype("float32"))
    with autograd.train_mode():
        net(x)
        net(x)
    sig_entries = list(net._jit_cache.values())
    assert sig_entries and all(e.get("fn_d") is None
                               for e in sig_entries)
