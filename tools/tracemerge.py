#!/usr/bin/env python
"""Cross-process runlog merger (``tools/tracemerge.py``).

Round 20: every process in the system (FleetRouter, its replica
subprocesses, the online trainer, healing relaunches, bench itself)
writes an isolated runlog, and round 20's tracing module stamps their
records with W3C-style ``trace_id``/``span_id``/``parent_span_id``
plus cross-boundary links (HTTP ``traceparent`` hop, the
``MXNET_TRACE_CONTEXT`` env stamp, the artifact ``trace_anchor``).
This tool is the read side: it folds N per-process runlogs into ONE
causally-linked timeline.

* ``merge`` — emit a single Perfetto/Chrome-trace JSON: one track
  group per process (named from the round-20 ``run_start``
  role/rank/pid identity), one sub-track per in-flight request, and
  flow arrows on every cross-process parent link (router hop ->
  replica request, trainer export -> rolling swap).
* ``doctor`` — per-request bottleneck attribution: decompose each
  routed request into queue / coalesce / compute / other against its
  end-to-end span, report fleet-wide percentages, flag requests that
  overlapped a ``rolling_swap``, and NAME the process (replica) whose
  compute dominates — the "which replica is slow" answer.
* ``prom-aggregate`` (also spelled ``--prom-aggregate``) — fold
  per-replica Prometheus textfiles into one scrape file: counters
  summed, gauges max-ed, TYPE lines preserved.

Clock skew: wall clocks across processes are NOT trusted.  For every
process pair linked by a request-response span pair (a ``client`` span
whose id is the ``parent_span_id`` of a ``server`` span in another
process) the offset is estimated NTP-style — midpoint of the feasible
interval, ``((t2-t1)+(t3-t4))/2`` — and the per-pair MEDIAN is
propagated from the reference process (the router when present)
through the pair graph.  A process with no pair path falls back to
healing beat files (``--beats DIR``: the ``rank-N.hb`` payload wall
time vs file mtime puts every beater on the shared filesystem clock)
and, failing that, to its ``run_start`` wall clock as-is.

Wall-time reconstruction: a span record stores run-relative END time
``t`` (perf_counter based) plus ``dur_ms``; its wall interval is
``run_start.time + t - dur_ms/1e3 .. run_start.time + t``.

Stdlib only — this tool must run anywhere the runlogs land.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

__all__ = [
    "load_runlog", "load_runlogs", "estimate_offsets", "merge_trace",
    "doctor", "aggregate_textfiles", "main",
]

#: span kinds forming a cross-process request-response pair
_CLIENT = "client"
_SERVER = "server"


# ----------------------------------------------------------------- load
def load_runlog(path):
    """Parse one runlog into a process dict::

        {path, label, pid, role, rank, start (run_start wall time),
         spans: [span dicts + t_start/t_end wall times],
         marks: [trace-stamped non-span records]}

    Malformed lines are skipped (a crashed process may leave a torn
    tail); a missing ``run_start`` makes the log unusable and returns
    None.
    """
    proc = {"path": os.fspath(path), "pid": None, "role": None,
            "rank": None, "start": None, "spans": [], "marks": []}
    try:
        f = open(path, "r", errors="replace")
    except OSError:
        return None
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            t = rec.get("type")
            if t == "run_start":
                proc["start"] = float(rec.get("time", 0.0))
                proc["pid"] = rec.get("pid")
                proc["role"] = rec.get("role")
                proc["rank"] = rec.get("rank")
            elif t == "span":
                if proc["start"] is None:
                    continue
                try:
                    end = proc["start"] + float(rec["t"])
                    dur = float(rec["dur_ms"]) / 1e3
                except (KeyError, TypeError, ValueError):
                    continue
                s = dict(rec)
                s["t_end"] = end
                s["t_start"] = end - dur
                proc["spans"].append(s)
            elif "trace_id" in rec and proc["start"] is not None \
                    and isinstance(rec.get("t"), (int, float)):
                proc["marks"].append(dict(rec))
    if proc["start"] is None:
        return None
    base = os.path.basename(proc["path"])
    stem = base[:-6] if base.endswith(".jsonl") else base
    if proc["role"]:
        label = proc["role"]
        if proc["rank"] is not None:
            label += f"-{proc['rank']}"
    else:
        label = stem
    proc["label"] = f"{label} (pid {proc['pid']})"
    return proc


def load_runlogs(paths):
    """Expand dirs to ``*.jsonl``, load each, drop unusable logs."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            files.append(p)
    procs = []
    for p in files:
        proc = load_runlog(p)
        if proc is not None and (proc["spans"] or proc["marks"]):
            procs.append(proc)
        elif proc is not None:
            procs.append(proc)  # identity-only logs still get a track
    return procs


# ----------------------------------------------------------------- skew
def _median(xs):
    xs = sorted(xs)
    n = len(xs)
    return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])


def _beat_offsets(beats_dir):
    """pid -> (payload wall time - file mtime): how far that process's
    wall clock ran ahead of the shared filesystem clock when it last
    beat.  Subtracting pairs of these aligns any two beaters."""
    out = {}
    if not beats_dir:
        return out
    for path in sorted(glob.glob(os.path.join(
            os.fspath(beats_dir), "*.hb"))):
        try:
            mtime = os.stat(path).st_mtime
            with open(path) as f:
                payload = json.load(f)
            out[int(payload["pid"])] = float(payload["time"]) - mtime
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def estimate_offsets(procs, beats_dir=None):
    """Per-process clock offsets (seconds to SUBTRACT from that
    process's wall times to land in the reference clock frame).

    Returns ``(offsets, info)`` where ``offsets[i]`` indexes ``procs``
    and ``info`` reports the reference index, per-edge pair counts and
    which processes fell back (``beats`` / ``wall``).
    """
    n = len(procs)
    by_span = []  # per process: span_id -> span
    for p in procs:
        by_span.append({s.get("span_id"): s for s in p["spans"]
                        if s.get("span_id")})
    # pairwise NTP samples: edge (a, b) -> [offset of b relative to a]
    samples = {}
    for b, pb in enumerate(procs):
        for s in pb["spans"]:
            parent = s.get("parent_span_id")
            if not parent or s.get("kind") != _SERVER:
                continue
            for a in range(n):
                if a == b:
                    continue
                ps = by_span[a].get(parent)
                if ps is None or ps.get("kind") != _CLIENT:
                    continue
                # t1..t4: client send, server recv, server send,
                # client recv — midpoint of the feasible interval
                t1, t4 = ps["t_start"], ps["t_end"]
                t2, t3 = s["t_start"], s["t_end"]
                theta = ((t2 - t1) + (t3 - t4)) / 2.0
                samples.setdefault((a, b), []).append(theta)
    edges = {e: _median(v) for e, v in samples.items()}
    # reference: the router when present, else the process with the
    # most client spans (it anchors the most edges), else the first
    ref = 0
    for i, p in enumerate(procs):
        if p["role"] == "router":
            ref = i
            break
    else:
        best = -1
        for i, p in enumerate(procs):
            k = sum(1 for s in p["spans"] if s.get("kind") == _CLIENT)
            if k > best:
                best, ref = k, i
    offsets = {ref: 0.0}
    frontier = [ref]
    while frontier:
        nxt = []
        for a in frontier:
            for (x, y), th in edges.items():
                if x == a and y not in offsets:
                    offsets[y] = offsets[a] + th
                    nxt.append(y)
                elif y == a and x not in offsets:
                    offsets[x] = offsets[a] - th
                    nxt.append(x)
        frontier = nxt
    fallback = {}
    missing = [i for i in range(n) if i not in offsets]
    if missing:
        beats = _beat_offsets(beats_dir)
        ref_beat = beats.get(procs[ref]["pid"], 0.0)
        for i in missing:
            b = beats.get(procs[i]["pid"])
            if b is not None:
                # both sides measured against the filesystem clock
                offsets[i] = b - ref_beat
                fallback[i] = "beats"
            else:
                offsets[i] = 0.0   # trust run_start wall clock
                fallback[i] = "wall"
    info = {"reference": ref,
            "pairs": {f"{a}->{b}": len(v)
                      for (a, b), v in samples.items()},
            "fallback": {procs[i]["label"]: how
                         for i, how in fallback.items()}}
    return offsets, info


# ---------------------------------------------------------------- merge
def merge_trace(procs, beats_dir=None, trace_id=None):
    """Fold loaded runlogs into one Chrome-trace/Perfetto JSON dict.

    One track group (pid) per process, one sub-track (tid) per
    trace_id within a process, ``X`` duration events per span, ``i``
    instants for trace-stamped non-span records, and ``s``/``f`` flow
    arrows on every cross-process parent link.
    """
    offsets, info = estimate_offsets(procs, beats_dir)
    # corrected wall times; epoch = earliest corrected instant
    t0 = None
    for i, p in enumerate(procs):
        off = offsets[i]
        for s in p["spans"]:
            ts = s["t_start"] - off
            t0 = ts if t0 is None or ts < t0 else t0
        for m in p["marks"]:
            ts = p["start"] + float(m["t"]) - off
            t0 = ts if t0 is None or ts < t0 else t0
    if t0 is None:
        t0 = 0.0
    events = []
    span_proc = {}   # span_id -> (pid, tid, corrected start us)
    child_links = []  # (parent_span_id, pid, tid, ts_us, span_id)
    for i, p in enumerate(procs):
        off = offsets[i]
        pid = p["pid"] if isinstance(p["pid"], int) else i + 1
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": p["label"]}})
        tids = {}

        def tid_for(tr):
            if tr not in tids:
                tids[tr] = len(tids) + 1
            return tids[tr]

        for s in p["spans"]:
            if trace_id is not None and s.get("trace_id") != trace_id:
                continue
            tid = tid_for(s.get("trace_id"))
            ts = (s["t_start"] - off - t0) * 1e6
            dur = max(0.0, float(s.get("dur_ms", 0.0)) * 1e3)
            args = {k: s[k] for k in ("trace_id", "span_id",
                                      "parent_span_id") if s.get(k)}
            args.update(s.get("attrs") or {})
            events.append({"ph": "X", "name": s.get("name", "span"),
                           "cat": s.get("kind", "internal"),
                           "pid": pid, "tid": tid,
                           "ts": round(ts, 3), "dur": round(dur, 3),
                           "args": args})
            sid = s.get("span_id")
            if sid:
                span_proc[sid] = (pid, tid, ts)
            par = s.get("parent_span_id")
            if par:
                child_links.append((par, pid, tid, ts, sid))
        for m in p["marks"]:
            if trace_id is not None and m.get("trace_id") != trace_id:
                continue
            tid = tid_for(m.get("trace_id"))
            ts = (p["start"] + float(m["t"]) - off - t0) * 1e6
            events.append({"ph": "i", "s": "t",
                           "name": m.get("type", "mark"),
                           "cat": "record", "pid": pid, "tid": tid,
                           "ts": round(ts, 3),
                           "args": {"span_id": m.get("span_id")}})
    # flow arrows: only where the parent lives in ANOTHER track group
    # (same-process nesting is already visible on the track)
    flow_id = 0
    for par, pid, tid, ts, sid in child_links:
        src = span_proc.get(par)
        if src is None or src[0] == pid:
            continue
        flow_id += 1
        spid, stid, sts = src
        events.append({"ph": "s", "id": flow_id, "name": "link",
                       "cat": "trace", "pid": spid, "tid": stid,
                       "ts": round(sts, 3)})
        events.append({"ph": "f", "bp": "e", "id": flow_id,
                       "name": "link", "cat": "trace", "pid": pid,
                       "tid": tid, "ts": round(ts, 3)})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {
                "tool": "tracemerge",
                "processes": [p["label"] for p in procs],
                "reference": procs[info["reference"]]["label"],
                "skew_s": {procs[i]["label"]: round(offsets[i], 6)
                           for i in range(len(procs))},
                "pairs": info["pairs"],
                "fallback": info["fallback"],
                "epoch": t0,
            }}


# --------------------------------------------------------------- doctor
#: per-request phase spans -> doctor component
_PHASES = {"serve_queue": "queue", "serve_coalesce": "coalesce",
           "serve_model": "compute", "gen_admit": "queue",
           "gen_prefill": "compute", "gen_decode": "compute"}
_ROOTS = ("fleet_request", "gen_request")


def doctor(procs, beats_dir=None):
    """Bottleneck attribution across routed requests.

    Returns a dict: per-component totals/percentages, the dominant
    component, requests overlapping a ``rolling_swap`` (the
    swap-in-progress bucket), and the per-process compute ranking that
    names the slow replica.
    """
    offsets, info = estimate_offsets(procs, beats_dir)
    spans = []
    for i, p in enumerate(procs):
        off = offsets[i]
        for s in p["spans"]:
            c = dict(s)
            c["t_start"] -= off
            c["t_end"] -= off
            c["proc"] = i
            spans.append(c)
    roots = [s for s in spans if s.get("name") in _ROOTS]
    swaps = [s for s in spans if s.get("name") == "rolling_swap"]
    by_trace = {}
    for s in spans:
        by_trace.setdefault(s.get("trace_id"), []).append(s)
    comp = {"queue": 0.0, "coalesce": 0.0, "compute": 0.0,
            "other": 0.0}
    per_proc_compute = {}
    e2e_total = 0.0
    swap_overlapped = 0
    requests = []
    for root in roots:
        tr = by_trace.get(root.get("trace_id"), [])
        e2e = max(0.0, root["t_end"] - root["t_start"])
        parts = {"queue": 0.0, "coalesce": 0.0, "compute": 0.0}
        for s in tr:
            phase = _PHASES.get(s.get("name"))
            if phase is None or s is root:
                continue
            d = max(0.0, s["t_end"] - s["t_start"])
            parts[phase] += d
            if phase == "compute":
                per = per_proc_compute.setdefault(
                    s["proc"], {"total": 0.0, "n": 0})
                per["total"] += d
                per["n"] += 1
        other = max(0.0, e2e - sum(parts.values()))
        overlaps = any(sw["t_start"] < root["t_end"]
                       and sw["t_end"] > root["t_start"]
                       for sw in swaps)
        if overlaps:
            swap_overlapped += 1
        for k, v in parts.items():
            comp[k] += v
        comp["other"] += other
        e2e_total += e2e
        requests.append({"trace_id": root.get("trace_id"),
                         "name": root.get("name"), "e2e_ms": e2e * 1e3,
                         "parts_ms": {k: v * 1e3
                                      for k, v in parts.items()},
                         "other_ms": other * 1e3,
                         "swap_in_progress": overlaps})
    pct = {k: (100.0 * v / e2e_total if e2e_total > 0 else 0.0)
           for k, v in comp.items()}
    dominant = max(pct, key=pct.get) if requests else None
    if swap_overlapped and requests \
            and swap_overlapped >= len(requests) / 2:
        dominant = "swap-in-progress"
    ranking = sorted(
        ({"process": procs[i]["label"],
          "mean_compute_ms": v["total"] / v["n"] * 1e3,
          "spans": v["n"]}
         for i, v in per_proc_compute.items() if v["n"]),
        key=lambda r: -r["mean_compute_ms"])
    return {"requests": len(requests), "processes": len(procs),
            "e2e_total_ms": e2e_total * 1e3,
            "components_pct": {k: round(v, 2) for k, v in pct.items()},
            "dominant": dominant,
            "swap_in_progress_requests": swap_overlapped,
            "compute_ranking": ranking,
            "bottleneck_process": (ranking[0]["process"]
                                   if ranking else None),
            "skew_s": {procs[i]["label"]: round(offsets[i], 6)
                       for i in range(len(procs))},
            "per_request": requests}


def _render_doctor(rep):
    lines = [f"tracemerge doctor: {rep['requests']} request(s) "
             f"across {rep['processes']} process(es)"]
    for k in ("queue", "coalesce", "compute", "other"):
        lines.append(f"  {k:<9} {rep['components_pct'][k]:6.1f}%")
    lines.append(f"  swap-in-progress: "
                 f"{rep['swap_in_progress_requests']} request(s) "
                 f"overlapped a rolling_swap")
    if rep["dominant"] is not None:
        lines.append(f"  dominant: {rep['dominant']}")
    for r in rep["compute_ranking"]:
        lines.append(f"    {r['process']}: mean serve_model "
                     f"{r['mean_compute_ms']:.2f} ms "
                     f"({r['spans']} span(s))")
    if rep["bottleneck_process"] is not None:
        lines.append(f"  bottleneck process: "
                     f"{rep['bottleneck_process']}")
    return "\n".join(lines)


# ------------------------------------------------------------- prom agg
def aggregate_textfiles(paths):
    """Fold Prometheus textfiles into one scrape body: counters
    SUMMED, gauges MAX-ed (a fleet-wide ready gauge is "any replica
    ready" = max; a fleet-wide request count is the sum).  Metric
    identity includes labels; TYPE lines are emitted once per family
    in first-seen order."""
    kinds = {}    # family -> counter|gauge
    values = {}   # full metric name (incl labels) -> folded value
    order = []    # first-seen metric order
    for path in paths:
        try:
            with open(path) as f:
                body = f.read()
        except OSError:
            continue
        for line in body.splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("# TYPE "):
                try:
                    _, _, family, kind = line.split(None, 3)
                except ValueError:
                    continue
                kinds.setdefault(family, kind)
                continue
            if line.startswith("#"):
                continue
            try:
                name, raw = line.rsplit(None, 1)
                val = float(raw)
            except ValueError:
                continue
            family = name.split("{", 1)[0]
            kind = kinds.get(family, "gauge")
            if name not in values:
                values[name] = val
                order.append(name)
            elif kind == "counter":
                values[name] += val
            else:
                values[name] = max(values[name], val)
    lines = []
    typed = set()
    for name in order:
        family = name.split("{", 1)[0]
        if family not in typed:
            typed.add(family)
            lines.append(f"# TYPE {family} {kinds.get(family, 'gauge')}")
        v = values[name]
        out = int(v) if float(v).is_integer() else v
        lines.append(f"{name} {out}")
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------------ CLI
def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # "--prom-aggregate f1 f2" is sugar for the prom-aggregate command
    if argv and argv[0] == "--prom-aggregate":
        argv[0] = "prom-aggregate"
    ap = argparse.ArgumentParser(
        prog="tools/tracemerge.py",
        description="merge per-process runlogs into one causal "
        "timeline (Perfetto), diagnose per-request bottlenecks, "
        "aggregate Prometheus textfiles")
    sub = ap.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser("merge", help="emit one merged Perfetto trace")
    pm.add_argument("logs", nargs="+",
                    help="runlog .jsonl files and/or runlog_dir dirs")
    pm.add_argument("-o", "--out", default="-",
                    help="output path (default stdout)")
    pm.add_argument("--trace", default=None,
                    help="restrict to one trace_id")
    pm.add_argument("--beats", default=None,
                    help="healing heartbeat dir (skew fallback)")
    pd = sub.add_parser("doctor", help="per-request bottleneck "
                        "attribution")
    pd.add_argument("logs", nargs="+")
    pd.add_argument("--beats", default=None)
    pd.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    pp = sub.add_parser("prom-aggregate", help="fold per-replica "
                        "textfiles into one scrape file")
    pp.add_argument("files", nargs="+")
    pp.add_argument("-o", "--out", default="-")
    args = ap.parse_args(argv)
    if args.cmd == "prom-aggregate":
        body = aggregate_textfiles(args.files)
        if args.out == "-":
            sys.stdout.write(body)
        else:
            with open(args.out, "w") as f:
                f.write(body)
        return 0
    procs = load_runlogs(args.logs)
    if not procs:
        print("tracemerge: no usable runlogs", file=sys.stderr)
        return 2
    if args.cmd == "merge":
        trace = merge_trace(procs, beats_dir=args.beats,
                            trace_id=args.trace)
        body = json.dumps(trace, sort_keys=True)
        if args.out == "-":
            sys.stdout.write(body + "\n")
        else:
            with open(args.out, "w") as f:
                f.write(body)
            print(f"tracemerge: wrote {args.out} "
                  f"({len(trace['traceEvents'])} events, "
                  f"{len(procs)} process(es))")
        return 0
    rep = doctor(procs, beats_dir=args.beats)
    if args.json:
        full = dict(rep)
        print(json.dumps(full, sort_keys=True))
    else:
        print(_render_doctor(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
