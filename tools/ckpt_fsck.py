#!/usr/bin/env python
"""Offline checkpoint verifier (``fsck`` for the atomic-checkpoint
format): walk a checkpoint prefix or directory, re-read every payload
against its manifest's size+CRC32 (``CheckpointManager.verify``), and
exit nonzero NAMING the first torn/corrupt file.

Usage::

    python tools/ckpt_fsck.py PREFIX_OR_DIR [--all] [--json]

* ``PREFIX_OR_DIR`` — a checkpoint prefix (``/run/ck``) or a
  directory; a directory is scanned for every prefix that owns a
  ``*-NNNN.manifest.json``.
* default: verify only the version the ``latest`` pointer chain would
  recover (the newest version that verifies must be the newest version
  on disk — an out-of-date recovery point is reported).
* ``--all`` — verify EVERY version of every prefix (what the chaos
  campaign runs after each seeded fault: zero torn artifacts).
* ``--json`` — machine-readable report on stdout.

Exit status: 0 = clean, 1 = corruption found (first problem printed),
2 = nothing to check (no manifests under the argument).

Stray ``.tmp.*`` files (a crash mid-atomic-write leaves the temp, the
final path untouched) are reported as informational, never an error —
they are the PROOF the tear did not reach the real artifact.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_MANIFEST_RE = re.compile(r"^(?P<base>.+)-(?P<ver>\d+)\.manifest\.json$")


def discover_prefixes(arg):
    """Checkpoint prefixes under ``arg``: the argument itself when it
    is a prefix (owns at least one manifest), else every distinct
    ``<dir>/<base>`` with a manifest inside the directory."""
    if os.path.isdir(arg):
        bases = set()
        for name in sorted(os.listdir(arg)):
            m = _MANIFEST_RE.match(name)
            if m:
                bases.add(os.path.join(arg, m.group("base")))
        return sorted(bases)
    return [arg]


def stray_temps(prefix):
    d = os.path.dirname(os.path.abspath(prefix)) or "."
    base = os.path.basename(prefix)
    try:
        return sorted(n for n in os.listdir(d)
                      if n.startswith(f".{base}") and ".tmp." in n)
    except OSError:
        return []


def fsck(arg, check_all=False):
    """Verify checkpoints under ``arg``; returns the report dict
    (``clean`` / ``problems`` / per-prefix detail)."""
    from mxnet_tpu.resilience.checkpoint import CheckpointManager

    prefixes = discover_prefixes(arg)
    report = {"target": os.fspath(arg), "mode": "all" if check_all
              else "latest", "prefixes": [], "problems": [],
              "versions_checked": 0, "clean": True}
    for prefix in prefixes:
        mgr = CheckpointManager(prefix)
        eps = mgr.epochs()
        entry = {"prefix": prefix, "versions": eps,
                 "stray_temps": stray_temps(prefix), "checked": [],
                 "bad": []}
        report["prefixes"].append(entry)
        if not eps:
            continue
        # parameter-shard recognition: a zero3-stamped topology means
        # the run's LIVE params were flat bucket shards and the
        # .params payload is the host-gathered named layout — worth
        # naming in the report (informational; the CRC walk below is
        # layout-agnostic)
        try:
            topo = (mgr._read_manifest(eps[-1]) or {}).get(
                "topology") or {}
        except Exception:
            topo = {}
        if topo.get("sharding"):
            entry["sharding"] = topo["sharding"]
            if topo.get("zero_stage") is not None:
                entry["zero_stage"] = int(topo["zero_stage"])
            if topo.get("plan_fingerprint"):
                entry["plan_fingerprint"] = topo["plan_fingerprint"]
        to_check = eps if check_all else [eps[-1]]
        for e in to_check:
            report["versions_checked"] += 1
            entry["checked"].append(e)
            problem = mgr.verify_detail(e)
            if problem:
                entry["bad"].append({"version": e, "problem": problem})
                report["problems"].append(
                    f"{prefix}-{e:04d}: {problem}")
        if not check_all and entry["bad"]:
            # latest mode: the newest version is torn — say what the
            # recovery fallback would actually load
            good = mgr.latest_epoch()
            report["problems"].append(
                f"{prefix}: newest version {eps[-1]} is torn; "
                + (f"recovery falls back to version {good}"
                   if good is not None
                   else "NO version verifies — unrecoverable"))
    report["clean"] = not report["problems"]
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="ckpt_fsck",
        description="offline CRC/manifest verifier for atomic "
        "checkpoint series")
    ap.add_argument("target", help="checkpoint prefix or directory")
    ap.add_argument("--all", action="store_true",
                    help="verify every version (default: the newest)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)

    report = fsck(args.target, check_all=args.all)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        for entry in report["prefixes"]:
            print(f"{entry['prefix']}: versions={entry['versions']} "
                  f"checked={entry['checked']} "
                  f"bad={[b['version'] for b in entry['bad']]}")
            if entry.get("sharding") == "zero3":
                print("  note: parameter-shard checkpoint (ZeRO stage "
                      "3, plan "
                      f"{entry.get('plan_fingerprint', '?')}): the "
                      ".params payload is the host-gathered named "
                      "layout; resuming sharded re-shards via "
                      "stage3_load_params after a reshard_verdict "
                      "fingerprint check")
            for t in entry["stray_temps"]:
                print(f"  note: stray temp {t} (crash mid-write; "
                      "final artifact untouched)")
        for p in report["problems"]:
            print(f"CORRUPT: {p}")
        print("clean" if report["clean"] else
              f"{len(report['problems'])} problem(s)")
    if report["versions_checked"] == 0:
        print(f"ckpt_fsck: no checkpoint manifests under "
              f"{args.target!r}", file=sys.stderr)
        return 2
    return 0 if report["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
