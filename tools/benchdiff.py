#!/usr/bin/env python
"""Round-over-round bench trend differ (``tools/benchdiff.py``).

The repo commits one ``BENCH_rNN.json`` headline artifact and
(irregularly) one ``OPPERF_rNN.jsonl`` per-op artifact per round, but
until now nothing ever READ them as a sequence: BENCH_r05 sat in the
tree as ``rc: 124, parsed: null`` for a whole round and the only thing
that noticed was a human.  This tool turns the committed artifacts into
a machine-readable trend:

* **headline trend** — one row per round (value, MFU, ms/step, rc,
  degraded), with a verdict against the previous round that HAD a
  metric: ``ok`` / ``improved`` / ``regression``.  A round with no
  parsed metric (the r05 shape of failure) is a *regression with
  reason "missing metric"*, never a crash of this tool.
* **opperf trend** — per-op avg (and p50/p99 where present, so tail
  latency trends too) across rounds, with the worst slowdowns and best
  speedups between the last two rounds summarised.
* **fleet serving trend** (round 15) — the ``fleet`` INFERENCE
  phase's robustness metrics (p99_ms, shed rate, p99-within-SLO)
  round-over-round with the same baseline/ok/improved/regression
  verdicts the headline gets: a p99 past the threshold, a shed-rate
  jump, or an SLO flip is a REGRESSION; a round that HAD fleet data
  before and lost it is "missing fleet metric" — serving robustness
  regressions gate exactly like throughput ones.
* **quantization trend** (round 18; fp8 arm round 19) — the
  ``quantization`` INFERENCE phase's quantized-arm metrics
  round-over-round: top-1 agreement with the fp32 arm dropping below
  0.99 regresses ABSOLUTELY (accuracy is a floor, not a ratio) for
  BOTH the int8 and fp8 arms, the int8 p99 rates like the fleet's
  (lower is better), and a round that shipped a metric then lost it
  is "missing (fp8) quantization metric".
* **generate serving trend** (round 17) — the ``generate`` INFERENCE
  phase's paged-KV decode metrics round-over-round: decode tokens/s
  drops past the threshold or a TTFT-p99 blow-up regresses (lower
  TTFT is better, the fleet inversion), an int8 KV per-token
  agreement below 0.99 regresses ABSOLUTELY (the adoption floor),
  any post-warm compile regresses ABSOLUTELY (the zero-retrace
  contract), and a round that shipped the phase then lost it is
  "missing generate metric".
* **freshness trend** (round 18) — the ``freshness`` phase's online-
  learning metrics round-over-round: the fault-free sample-to-served
  p99 rates inverted like the fleet's (lower is better, past the
  threshold = regression), a served-version MONOTONICITY violation or
  a fault-free p99 over the SLO regresses ABSOLUTELY (a fleet that
  ever serves an older model, or misses its freshness promise, is
  broken at any speed — baseline rounds included), and a round that
  shipped the phase then lost it is "missing freshness metric".
* **trace trend** (round 20) — the ``trace`` phase's distributed-
  tracing metrics round-over-round: the traced-request p99 rates
  inverted like the fleet's, the armed-vs-unarmed submit overhead
  ratio must stay <= 2.0 ABSOLUTELY (the hot-path budget: spans ride
  existing flushes), a round whose p99 lacks its queue/coalesce/
  compute attribution or a named bottleneck process regresses
  ABSOLUTELY (a timeline that cannot say WHERE the time went is not
  observability), and a round that shipped the phase then lost it is
  "missing trace metric".
* **zero-stage trend** (round 16, ZeRO) — the collectives phase's
  ``zero`` block (stage-1 vs stage-3 sharded step on the virtual
  mesh): the per-step RS+AG bytes over the analytic plan minimum must
  stay <= 1.05 (extra bytes = a hidden gather or double exchange),
  the stage-3/stage-1 per-chip param+state ratio must stay within
  1.15x of the analytic 3/(N+2) floor, and the stage-3/stage-1 step
  time must stay <= 1.10 — each an ABSOLUTE budget, gated every round
  once the block ships; a round that then loses the block is
  "missing zero metric".

Exit code: 0 by default (reporting tool); ``--fail-on-regression``
exits 2 when the LATEST headline round regressed (or lost its metric)
beyond ``--threshold``, or any op slowed more than the threshold in
the latest opperf round — the CI gate ``benchdiff_smoke`` runs exactly
that over the committed artifacts.

Usage::

    python tools/benchdiff.py                      # repo-root defaults
    python tools/benchdiff.py --bench 'BENCH_r*.json' \
        --opperf 'OPPERF_r*.jsonl' --threshold 0.15 --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round_of(path):
    """'BENCH_r05.json' -> 'r05' (None when the name carries no round,
    e.g. OPPERF_smoke.jsonl)."""
    m = re.search(r"_r(\d+)\.", os.path.basename(path))
    return f"r{int(m.group(1)):02d}" if m else None


def load_bench(paths):
    """Parse headline artifacts into ``{round: row}``.

    Accepts both the driver wrapper shape (``{"n", "rc", "parsed"}``)
    and a bare headline JSON (bench.py's own stdout line, or a partial
    artifact).  A malformed file becomes a row with ``error`` — the
    differ reports it, it never crashes on it."""
    rounds = {}
    for path in paths:
        label = _round_of(path) or os.path.basename(path)
        row = {"file": os.path.basename(path), "value": None,
               "mfu": None, "ms_per_step": None, "rc": None,
               "degraded": None, "error": None,
               "fleet_p99_ms": None, "fleet_shed_rate": None,
               "fleet_within_slo": None,
               "fresh_p99_ms": None, "fresh_shed_rate": None,
               "fresh_within_slo": None, "fresh_monotonic": None,
               "quant_p99_ms": None, "quant_agreement": None,
               "quant_speedup": None, "quant_agreement_fp8": None,
               "gen_tokens_s": None, "gen_ttft_p99_ms": None,
               "gen_agreement": None, "gen_compiles": None,
               "zero_rs_ag_ratio": None, "zero_mem_ratio": None,
               "zero_mem_expected": None, "zero_step_ratio": None,
               "trace_p99_ms": None, "trace_overhead": None,
               "trace_processes": None, "trace_attributed": None,
               "trace_bottleneck": None}
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            row["error"] = f"unreadable: {e}"
            rounds[label] = row
            continue
        if isinstance(doc, dict) and "parsed" in doc:
            row["rc"] = doc.get("rc")
            if doc.get("n") is not None:
                label = f"r{int(doc['n']):02d}"
            parsed = doc.get("parsed")
        else:
            parsed = doc
        if isinstance(parsed, dict):
            row["value"] = parsed.get("value")
            row["mfu"] = parsed.get("mfu")
            row["ms_per_step"] = parsed.get("ms_per_step")
            row["degraded"] = parsed.get("degraded")
            fl = parsed.get("fleet")
            if isinstance(fl, dict) and fl.get("p99_ms") is not None:
                row["fleet_p99_ms"] = fl["p99_ms"]
                req = fl.get("requests") or 0
                row["fleet_shed_rate"] = round(
                    (fl.get("shed") or 0) / req, 4) if req else None
                row["fleet_within_slo"] = fl.get("p99_within_slo")
            fr = parsed.get("freshness")
            if isinstance(fr, dict) and fr.get("p99_ms") is not None:
                # the gate judges the fault-free p99 (tainted
                # post-heal samples are excluded, not hidden)
                row["fresh_p99_ms"] = (fr.get("fault_free_p99_ms")
                                       or fr["p99_ms"])
                row["fresh_within_slo"] = fr.get("p99_within_slo")
                row["fresh_monotonic"] = fr.get("monotonic")
                total = ((fr.get("swaps") or 0)
                         + (fr.get("swaps_shed") or 0))
                row["fresh_shed_rate"] = round(
                    (fr.get("swaps_shed") or 0) / total, 4) \
                    if total else None
            qt = parsed.get("quantization")
            if isinstance(qt, dict) \
                    and qt.get("agreement_top1") is not None:
                row["quant_agreement"] = qt["agreement_top1"]
                arm = qt.get("int8")
                if isinstance(arm, dict):
                    row["quant_p99_ms"] = arm.get("p99_ms")
                row["quant_speedup"] = qt.get("speedup_p50")
                row["quant_agreement_fp8"] = qt.get(
                    "agreement_top1_fp8")
            gen = parsed.get("generate")
            if isinstance(gen, dict) \
                    and gen.get("tokens_s") is not None:
                row["gen_tokens_s"] = gen["tokens_s"]
                row["gen_ttft_p99_ms"] = gen.get("ttft_p99_ms")
                row["gen_agreement"] = gen.get("kv_agreement")
                row["gen_compiles"] = gen.get("compiles_after_warm")
            tr = parsed.get("trace")
            if isinstance(tr, dict) and tr.get("processes") is not None:
                row["trace_p99_ms"] = tr.get("p99_ms")
                row["trace_overhead"] = tr.get("overhead_ratio")
                row["trace_processes"] = tr.get("processes")
                # "attribution present": the request p99 came with a
                # queue/coalesce/compute decomposition + a named
                # bottleneck — the observability deliverable itself
                comp = tr.get("components_pct")
                row["trace_attributed"] = bool(
                    isinstance(comp, dict)
                    and {"queue", "coalesce", "compute"} <= set(comp)
                    and tr.get("bottleneck_process") is not None)
                row["trace_bottleneck"] = tr.get("bottleneck_process")
            col = parsed.get("collectives")
            zr = col.get("zero") if isinstance(col, dict) else None
            if isinstance(zr, dict) \
                    and zr.get("mem_ratio") is not None:
                stage3 = zr.get("stage3") or {}
                row["zero_rs_ag_ratio"] = stage3.get("rs_ag_ratio")
                row["zero_mem_ratio"] = zr["mem_ratio"]
                row["zero_mem_expected"] = zr.get("mem_ratio_expected")
                row["zero_step_ratio"] = zr.get("step_ratio")
        rounds[label] = row
    return rounds


def headline_verdicts(rounds, threshold):
    """Attach a verdict per round vs the previous round that had a
    metric.  Missing metric = regression (reason says why), by design:
    that IS the r05 failure mode this tool exists to flag."""
    prev_value = None
    order = sorted(rounds)
    for label in order:
        row = rounds[label]
        v = row["value"]
        if v is None:
            rc = row["rc"]
            reason = "missing metric"
            if row["error"]:
                reason += f" ({row['error']})"
            elif rc not in (0, None):
                reason += f" (rc={rc})"
            row["verdict"] = "regression"
            row["reason"] = reason
            continue
        if prev_value is None:
            row["verdict"] = "baseline"
            row["reason"] = None
        else:
            change = v / prev_value - 1.0
            row["change"] = round(change, 4)
            if change < -threshold:
                row["verdict"] = "regression"
                row["reason"] = f"{change:+.1%} vs previous metric"
            elif change > threshold:
                row["verdict"] = "improved"
                row["reason"] = f"{change:+.1%} vs previous metric"
            else:
                row["verdict"] = "ok"
                row["reason"] = f"{change:+.1%} vs previous metric"
        prev_value = v
    return rounds


def fleet_verdicts(rounds, threshold):
    """Verdict the ``fleet`` serving phase round-over-round: LOWER
    p99 is better (the ratio check inverts vs the headline), a
    shed-rate jump past the threshold or an SLO verdict flipping
    false regresses too.  Rounds before the phase existed carry no
    fleet verdict at all; once a round HAS shipped fleet data, a
    later round without it is the r05 failure shape again —
    "missing fleet metric"."""
    seen = False
    prev = None
    for label in sorted(rounds):
        row = rounds[label]
        p99 = row["fleet_p99_ms"]
        if p99 is None:
            if seen:
                row["fleet_verdict"] = "regression"
                row["fleet_reason"] = "missing fleet metric"
            else:
                row["fleet_verdict"] = None
                row["fleet_reason"] = None
            continue
        shed = row["fleet_shed_rate"] or 0.0
        in_slo = row["fleet_within_slo"]
        if not seen:
            row["fleet_verdict"] = "baseline"
            row["fleet_reason"] = None
        else:
            p_p99, p_shed, p_slo = prev
            ratio = (p99 / p_p99) if p_p99 else None
            reasons = []
            if ratio is not None and ratio > 1.0 + threshold:
                reasons.append(f"p99 x{ratio:.2f}")
            if shed - p_shed > threshold:
                reasons.append(
                    f"shed rate {p_shed:.0%} -> {shed:.0%}")
            if p_slo and in_slo is False:
                reasons.append("p99 blew the SLO")
            if reasons:
                row["fleet_verdict"] = "regression"
                row["fleet_reason"] = "; ".join(reasons)
            elif ratio is not None and ratio < 1.0 / (1.0 + threshold):
                row["fleet_verdict"] = "improved"
                row["fleet_reason"] = f"p99 x{ratio:.2f}"
            else:
                row["fleet_verdict"] = "ok"
                row["fleet_reason"] = (f"p99 x{ratio:.2f}"
                                       if ratio is not None else None)
        seen = True
        prev = (p99, shed, bool(in_slo))
    return rounds


def quantization_verdicts(rounds, threshold):
    """Verdict the ``quantization`` INFERENCE phase round-over-round:
    top-1 agreement with the fp32 arm below 0.99 regresses ABSOLUTELY
    (the acceptance floor — quantization that changes answers is not
    a speed win), an agreement drop past the threshold vs the
    previous round regresses, and the int8 p99 rates inverted like
    the fleet's (lower is better).  Rounds before the phase existed
    carry no quantization verdict; once shipped, a later round
    without it is "missing quantization metric".  The fp8 arm (round
    19) is held to the SAME absolute 0.99 floor and the same
    missing-after-shipped gate, tracked independently — the fp8
    metric's shipping round may differ from int8's."""
    seen = False
    seen_fp8 = False
    prev = None
    for label in sorted(rounds):
        row = rounds[label]
        agreement = row["quant_agreement"]
        if agreement is None:
            if seen:
                row["quant_verdict"] = "regression"
                row["quant_reason"] = "missing quantization metric"
            else:
                row["quant_verdict"] = None
                row["quant_reason"] = None
            continue
        p99 = row["quant_p99_ms"]
        agreement_fp8 = row["quant_agreement_fp8"]
        reasons = []
        if agreement < 0.99:
            reasons.append(
                f"int8 agreement {agreement:.3f} < 0.99")
        if agreement_fp8 is not None:
            if agreement_fp8 < 0.99:
                reasons.append(
                    f"fp8 agreement {agreement_fp8:.3f} < 0.99")
        elif seen_fp8:
            reasons.append("missing fp8 quantization metric")
        if not seen:
            row["quant_verdict"] = "regression" if reasons \
                else "baseline"
            row["quant_reason"] = "; ".join(reasons) or None
        else:
            p_agree, p_p99 = prev
            ratio = (p99 / p_p99) if (p99 and p_p99) else None
            if p_agree - agreement > threshold:
                reasons.append(
                    f"agreement {p_agree:.3f} -> {agreement:.3f}")
            if ratio is not None and ratio > 1.0 + threshold:
                reasons.append(f"int8 p99 x{ratio:.2f}")
            if reasons:
                row["quant_verdict"] = "regression"
                row["quant_reason"] = "; ".join(reasons)
            elif ratio is not None and ratio < 1.0 / (1.0 + threshold):
                row["quant_verdict"] = "improved"
                row["quant_reason"] = f"int8 p99 x{ratio:.2f}"
            else:
                row["quant_verdict"] = "ok"
                row["quant_reason"] = (f"int8 p99 x{ratio:.2f}"
                                       if ratio is not None else None)
        seen = True
        seen_fp8 = seen_fp8 or agreement_fp8 is not None
        prev = (agreement, p99)
    return rounds


def generate_verdicts(rounds, threshold):
    """Verdict the ``generate`` INFERENCE phase round-over-round:
    decode tokens/s rates like the headline (higher is better), TTFT
    p99 rates inverted like the fleet's (lower is better), an int8 KV
    per-token agreement below 0.99 regresses ABSOLUTELY (the adoption
    floor — a KV cache that changes tokens is not a capacity win) and
    so does ANY post-warm compile (the zero-retrace contract of the
    compile-once decode loop).  Rounds before the phase existed carry
    no generate verdict; once shipped, a later round without it is
    "missing generate metric"."""
    seen = False
    prev = None
    for label in sorted(rounds):
        row = rounds[label]
        tok_s = row["gen_tokens_s"]
        if tok_s is None:
            if seen:
                row["gen_verdict"] = "regression"
                row["gen_reason"] = "missing generate metric"
            else:
                row["gen_verdict"] = None
                row["gen_reason"] = None
            continue
        ttft = row["gen_ttft_p99_ms"]
        agreement = row["gen_agreement"]
        compiles = row["gen_compiles"]
        reasons = []
        if agreement is not None and agreement < 0.99:
            reasons.append(
                f"int8 KV agreement {agreement:.3f} < 0.99")
        if compiles:
            reasons.append(
                f"{compiles} post-warm compile(s) (retrace)")
        if not seen:
            row["gen_verdict"] = "regression" if reasons \
                else "baseline"
            row["gen_reason"] = "; ".join(reasons) or None
        else:
            p_tok, p_ttft = prev
            tok_ratio = (tok_s / p_tok) if p_tok else None
            ttft_ratio = (ttft / p_ttft) if (ttft and p_ttft) else None
            if tok_ratio is not None \
                    and tok_ratio < 1.0 / (1.0 + threshold):
                reasons.append(f"tokens/s x{tok_ratio:.2f}")
            if ttft_ratio is not None and ttft_ratio > 1.0 + threshold:
                reasons.append(f"TTFT p99 x{ttft_ratio:.2f}")
            if reasons:
                row["gen_verdict"] = "regression"
                row["gen_reason"] = "; ".join(reasons)
            elif tok_ratio is not None \
                    and tok_ratio > 1.0 + threshold:
                row["gen_verdict"] = "improved"
                row["gen_reason"] = f"tokens/s x{tok_ratio:.2f}"
            else:
                row["gen_verdict"] = "ok"
                row["gen_reason"] = (f"tokens/s x{tok_ratio:.2f}"
                                     if tok_ratio is not None else None)
        seen = True
        prev = (tok_s, ttft)
    return rounds


def freshness_verdicts(rounds, threshold):
    """Verdict the ``freshness`` phase round-over-round: the
    fault-free sample-to-served p99 rates inverted like the fleet's
    (LOWER is better; past the threshold = regression).  Two verdicts
    are ABSOLUTE and fire even on the baseline round: a served-version
    monotonicity violation (a fleet that ever served an older model
    is broken at any speed — the no-regression contract of the
    rolling swap) and a fault-free p99 over the SLO (the promise the
    online loop exists to keep).  Rounds before the phase existed
    carry no verdict; once shipped, a later round without it is
    "missing freshness metric"."""
    seen = False
    prev = None
    for label in sorted(rounds):
        row = rounds[label]
        p99 = row["fresh_p99_ms"]
        if p99 is None:
            if seen:
                row["fresh_verdict"] = "regression"
                row["fresh_reason"] = "missing freshness metric"
            else:
                row["fresh_verdict"] = None
                row["fresh_reason"] = None
            continue
        reasons = []
        if row["fresh_monotonic"] is False:
            reasons.append("served versions went BACKWARDS")
        if row["fresh_within_slo"] is False:
            reasons.append("fault-free p99 over the freshness SLO")
        if not seen:
            row["fresh_verdict"] = "regression" if reasons \
                else "baseline"
            row["fresh_reason"] = "; ".join(reasons) or None
        else:
            ratio = (p99 / prev) if prev else None
            if ratio is not None and ratio > 1.0 + threshold:
                reasons.append(f"freshness p99 x{ratio:.2f}")
            if reasons:
                row["fresh_verdict"] = "regression"
                row["fresh_reason"] = "; ".join(reasons)
            elif ratio is not None \
                    and ratio < 1.0 / (1.0 + threshold):
                row["fresh_verdict"] = "improved"
                row["fresh_reason"] = f"freshness p99 x{ratio:.2f}"
            else:
                row["fresh_verdict"] = "ok"
                row["fresh_reason"] = (f"freshness p99 x{ratio:.2f}"
                                       if ratio is not None else None)
        seen = True
        prev = p99
    return rounds


#: armed-vs-unarmed submit p50 ratio budget: tracing must stay within
#: the PR-5 hot-path bound (spans ride existing flushes), so an armed
#: request path costing 2x an unarmed one is broken at any p99
TRACE_OVERHEAD_MAX = 2.0


def trace_verdicts(rounds, threshold):
    """Verdict the ``trace`` phase (round 20) round-over-round.  Two
    ABSOLUTE gates fire even on the baseline round: the request p99
    must come with its queue/coalesce/compute attribution and a named
    bottleneck process (a timeline that cannot say WHERE the time went
    is not observability), and the armed-vs-unarmed overhead ratio
    must stay under ``TRACE_OVERHEAD_MAX`` (the PR-5 hot-path bound,
    A/B-measured every round).  The traced-request p99 itself rates
    like the fleet's (lower is better, past the threshold =
    regression).  Rounds before the phase existed carry no verdict;
    once shipped, a later round without it is "missing trace
    metric"."""
    seen = False
    prev = None
    for label in sorted(rounds):
        row = rounds[label]
        p99 = row["trace_p99_ms"]
        if p99 is None and row["trace_processes"] is None:
            if seen:
                row["trace_verdict"] = "regression"
                row["trace_reason"] = "missing trace metric"
            else:
                row["trace_verdict"] = None
                row["trace_reason"] = None
            continue
        reasons = []
        if not row["trace_attributed"]:
            reasons.append("request p99 attribution missing")
        ov = row["trace_overhead"]
        if ov is not None and ov > TRACE_OVERHEAD_MAX:
            reasons.append(f"tracing overhead x{ov:.2f} "
                           f"(budget {TRACE_OVERHEAD_MAX:.1f})")
        if not seen:
            row["trace_verdict"] = "regression" if reasons \
                else "baseline"
            row["trace_reason"] = "; ".join(reasons) or None
        else:
            ratio = (p99 / prev) if prev and p99 is not None else None
            if ratio is not None and ratio > 1.0 + threshold:
                reasons.append(f"traced p99 x{ratio:.2f}")
            if reasons:
                row["trace_verdict"] = "regression"
                row["trace_reason"] = "; ".join(reasons)
            elif ratio is not None \
                    and ratio < 1.0 / (1.0 + threshold):
                row["trace_verdict"] = "improved"
                row["trace_reason"] = f"traced p99 x{ratio:.2f}"
            else:
                row["trace_verdict"] = "ok"
                row["trace_reason"] = (f"traced p99 x{ratio:.2f}"
                                       if ratio is not None else None)
        seen = True
        if p99 is not None:
            prev = p99
    return rounds


def zero_verdicts(rounds, threshold):
    """Verdict the collectives phase's ``zero`` block (ZeRO stage-1 vs
    stage-3 A/B) round-over-round.  Unlike the headline these are
    ABSOLUTE budgets, re-asserted every round the block ships:

    * ``rs_ag_ratio`` — measured per-step reduce-scatter+all-gather
      bytes over the plan's analytic minimum; > 1.05 means a hidden
      gather or a double exchange crept into the stage-3 program.
    * ``mem_ratio`` — stage-3/stage-1 per-chip param+opt-state bytes;
      more than 1.15x the analytic expectation (3/(N+2) for adam)
      means parameters stopped being sharded.
    * ``step_ratio`` — stage-3/stage-1 timed step; > 1.10 means the
      bucket-wise prefetch stopped hiding the gathers (the <=10%%
      step-time acceptance bound).

    Rounds before the block existed carry no zero verdict; once a
    round has shipped it, a later round without it is the r05 failure
    shape — "missing zero metric"."""
    seen = False
    for label in sorted(rounds):
        row = rounds[label]
        mem = row["zero_mem_ratio"]
        if mem is None:
            if seen:
                row["zero_verdict"] = "regression"
                row["zero_reason"] = "missing zero metric"
            else:
                row["zero_verdict"] = None
                row["zero_reason"] = None
            continue
        reasons = []
        wire = row["zero_rs_ag_ratio"]
        if wire is not None and wire > 1.05:
            reasons.append(f"RS+AG bytes x{wire:.2f} the analytic "
                           "minimum (> 1.05)")
        expected = row["zero_mem_expected"]
        if expected and mem > expected * 1.15:
            reasons.append(f"per-chip mem ratio {mem:.3f} > "
                           f"{expected:.3f} analytic x1.15")
        sr = row["zero_step_ratio"]
        if sr is not None and sr > 1.10:
            reasons.append(f"stage-3 step x{sr:.2f} stage-1 (> 1.10)")
        if reasons:
            row["zero_verdict"] = "regression"
            row["zero_reason"] = "; ".join(reasons)
        elif not seen:
            row["zero_verdict"] = "baseline"
            row["zero_reason"] = None
        else:
            row["zero_verdict"] = "ok"
            row["zero_reason"] = (
                f"wire x{wire:.2f}, mem {mem:.3f}, step x{sr:.2f}"
                if None not in (wire, sr) else None)
        seen = True
    return rounds


def load_opperf(paths):
    """``{round: {op: row}}`` from the per-op JSONL artifacts; rows
    keep avg and (when the artifact has them) p50/p99."""
    rounds = {}
    for path in paths:
        label = _round_of(path) or \
            os.path.splitext(os.path.basename(path))[0]
        ops = {}
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    if "op" not in row or "avg_time_ms" not in row:
                        continue
                    ops[row["op"]] = {
                        "avg_ms": row["avg_time_ms"],
                        "p50_ms": row.get("p50_time_ms"),
                        "p99_ms": row.get("p99_time_ms"),
                    }
        except OSError:
            continue
        if ops:
            rounds[label] = ops
    return rounds


def opperf_diff(rounds, threshold):
    """Compare the last two opperf rounds: per-op avg ratio (and p99
    ratio where both rounds have it), split into regressions (slower
    than 1+threshold) and improvements."""
    order = sorted(rounds)
    if len(order) < 2:
        return {"rounds": order, "regressions": [], "improvements": [],
                "compared_ops": 0}
    prev_label, last_label = order[-2], order[-1]
    prev, last = rounds[prev_label], rounds[last_label]
    regs, imps = [], []
    compared = 0
    for op in sorted(set(prev) & set(last)):
        a, b = prev[op]["avg_ms"], last[op]["avg_ms"]
        if not (isinstance(a, (int, float))
                and isinstance(b, (int, float))) or a <= 0 or b <= 0:
            continue
        compared += 1
        ratio = b / a
        ent = {"op": op, "prev_ms": a, "last_ms": b,
               "ratio": round(ratio, 3)}
        if prev[op].get("p99_ms") and last[op].get("p99_ms"):
            ent["p99_ratio"] = round(
                last[op]["p99_ms"] / prev[op]["p99_ms"], 3)
        if ratio > 1.0 + threshold:
            regs.append(ent)
        elif ratio < 1.0 / (1.0 + threshold):
            imps.append(ent)
    regs.sort(key=lambda e: e["ratio"], reverse=True)
    imps.sort(key=lambda e: e["ratio"])
    return {"rounds": order, "prev": prev_label, "last": last_label,
            "compared_ops": compared, "regressions": regs,
            "improvements": imps}


def _fmt(v, spec="{:.2f}"):
    return "-" if v is None else spec.format(v)


def render(bench, opperf, threshold):
    lines = [f"== headline trend (threshold {threshold:.0%}) =="]
    lines.append(f"{'round':<10s}{'value':>12s}{'mfu':>8s}"
                 f"{'ms/step':>10s}{'rc':>5s}{'degraded':>10s}"
                 f"  verdict")
    for label in sorted(bench):
        r = bench[label]
        verdict = r["verdict"]
        if r.get("reason"):
            verdict += f": {r['reason']}"
        lines.append(
            f"{label:<10s}{_fmt(r['value']):>12s}"
            f"{_fmt(r['mfu'], '{:.3f}'):>8s}"
            f"{_fmt(r['ms_per_step']):>10s}"
            f"{('-' if r['rc'] is None else str(r['rc'])):>5s}"
            f"{('-' if r['degraded'] is None else str(r['degraded'])):>10s}"
            f"  {verdict}")
    quant_rows = [label for label in sorted(bench)
                  if bench[label].get("quant_verdict")]
    if quant_rows:
        lines.append("")
        lines.append("== quantization trend ==")
        lines.append(f"{'round':<10s}{'agree':>8s}{'p99_ms':>10s}"
                     f"{'x_p50':>8s}  verdict")
        for label in quant_rows:
            r = bench[label]
            verdict = r["quant_verdict"]
            if r.get("quant_reason"):
                verdict += f": {r['quant_reason']}"
            ag = r["quant_agreement"]
            lines.append(
                f"{label:<10s}"
                f"{('-' if ag is None else f'{ag:.3f}'):>8s}"
                f"{_fmt(r['quant_p99_ms']):>10s}"
                f"{_fmt(r['quant_speedup']):>8s}"
                f"  {verdict}")
    gen_rows = [label for label in sorted(bench)
                if bench[label].get("gen_verdict")]
    if gen_rows:
        lines.append("")
        lines.append("== generate serving trend ==")
        lines.append(f"{'round':<10s}{'tok/s':>10s}{'ttft_p99':>10s}"
                     f"{'agree':>8s}{'retrace':>9s}  verdict")
        for label in gen_rows:
            r = bench[label]
            verdict = r["gen_verdict"]
            if r.get("gen_reason"):
                verdict += f": {r['gen_reason']}"
            ag = r["gen_agreement"]
            comp = r["gen_compiles"]
            lines.append(
                f"{label:<10s}"
                f"{_fmt(r['gen_tokens_s']):>10s}"
                f"{_fmt(r['gen_ttft_p99_ms']):>10s}"
                f"{('-' if ag is None else f'{ag:.3f}'):>8s}"
                f"{('-' if comp is None else str(comp)):>9s}"
                f"  {verdict}")
    zero_rows = [label for label in sorted(bench)
                 if bench[label].get("zero_verdict")]
    if zero_rows:
        lines.append("")
        lines.append("== zero-stage trend (stage-3 vs stage-1) ==")
        lines.append(f"{'round':<10s}{'wire':>8s}{'mem':>8s}"
                     f"{'mem_exp':>9s}{'step':>8s}  verdict")
        for label in zero_rows:
            r = bench[label]
            verdict = r["zero_verdict"]
            if r.get("zero_reason"):
                verdict += f": {r['zero_reason']}"
            lines.append(
                f"{label:<10s}"
                f"{_fmt(r['zero_rs_ag_ratio']):>8s}"
                f"{_fmt(r['zero_mem_ratio'], '{:.3f}'):>8s}"
                f"{_fmt(r['zero_mem_expected'], '{:.3f}'):>9s}"
                f"{_fmt(r['zero_step_ratio']):>8s}"
                f"  {verdict}")
    fleet_rows = [label for label in sorted(bench)
                  if bench[label].get("fleet_verdict")]
    if fleet_rows:
        lines.append("")
        lines.append("== fleet serving trend ==")
        lines.append(f"{'round':<10s}{'p99_ms':>10s}{'shed':>8s}"
                     f"{'in_slo':>8s}  verdict")
        for label in fleet_rows:
            r = bench[label]
            verdict = r["fleet_verdict"]
            if r.get("fleet_reason"):
                verdict += f": {r['fleet_reason']}"
            shed = r["fleet_shed_rate"]
            lines.append(
                f"{label:<10s}"
                f"{_fmt(r['fleet_p99_ms']):>10s}"
                f"{('-' if shed is None else f'{shed:.0%}'):>8s}"
                f"{('-' if r['fleet_within_slo'] is None else str(r['fleet_within_slo'])):>8s}"
                f"  {verdict}")
    fresh_rows = [label for label in sorted(bench)
                  if bench[label].get("fresh_verdict")]
    if fresh_rows:
        lines.append("")
        lines.append("== freshness trend (online learning) ==")
        lines.append(f"{'round':<10s}{'p99_ms':>10s}{'shed':>8s}"
                     f"{'in_slo':>8s}{'mono':>7s}  verdict")
        for label in fresh_rows:
            r = bench[label]
            verdict = r["fresh_verdict"]
            if r.get("fresh_reason"):
                verdict += f": {r['fresh_reason']}"
            shed = r["fresh_shed_rate"]
            lines.append(
                f"{label:<10s}"
                f"{_fmt(r['fresh_p99_ms']):>10s}"
                f"{('-' if shed is None else f'{shed:.0%}'):>8s}"
                f"{('-' if r['fresh_within_slo'] is None else str(r['fresh_within_slo'])):>8s}"
                f"{('-' if r['fresh_monotonic'] is None else str(r['fresh_monotonic'])):>7s}"
                f"  {verdict}")
    trace_rows = [label for label in sorted(bench)
                  if bench[label].get("trace_verdict")]
    if trace_rows:
        lines.append("")
        lines.append("== trace trend (distributed tracing) ==")
        lines.append(f"{'round':<10s}{'p99_ms':>10s}{'procs':>7s}"
                     f"{'ovhd':>7s}{'attr':>6s}  verdict")
        for label in trace_rows:
            r = bench[label]
            verdict = r["trace_verdict"]
            if r.get("trace_reason"):
                verdict += f": {r['trace_reason']}"
            ov = r["trace_overhead"]
            lines.append(
                f"{label:<10s}"
                f"{_fmt(r['trace_p99_ms']):>10s}"
                f"{('-' if r['trace_processes'] is None else str(r['trace_processes'])):>7s}"
                f"{('-' if ov is None else f'x{ov:.2f}'):>7s}"
                f"{('-' if r['trace_attributed'] is None else str(bool(r['trace_attributed']))):>6s}"
                f"  {verdict}")
    if opperf.get("compared_ops"):
        lines.append("")
        lines.append(f"== opperf trend {opperf['prev']} -> "
                     f"{opperf['last']} "
                     f"({opperf['compared_ops']} ops compared) ==")
        for title, ents in (("slower", opperf["regressions"][:10]),
                            ("faster", opperf["improvements"][:10])):
            if not ents:
                continue
            lines.append(f"-- top {title} --")
            for e in ents:
                p99 = f" p99x{e['p99_ratio']}" if "p99_ratio" in e \
                    else ""
                lines.append(
                    f"  {e['op']:<40.40s} {e['prev_ms']:>10.4f} -> "
                    f"{e['last_ms']:>10.4f} ms  x{e['ratio']}{p99}")
    elif opperf.get("rounds"):
        lines.append("")
        lines.append(f"== opperf: {len(opperf['rounds'])} round(s), "
                     "need 2+ to diff ==")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--bench", default=None,
                    help="glob of headline artifacts (default "
                         "BENCH_r*.json in the repo root)")
    ap.add_argument("--opperf", default=None,
                    help="glob of per-op artifacts (default "
                         "OPPERF_r*.jsonl in the repo root)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="regression threshold as a fraction "
                         "(default 0.15 = 15%%)")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 2 when the latest headline round "
                         "regressed/lost its metric, or the latest "
                         "opperf round has ops slower than the "
                         "threshold")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="emit the machine-readable summary instead "
                         "of the table")
    args = ap.parse_args(argv)

    bench_glob = args.bench or os.path.join(_REPO, "BENCH_r*.json")
    opperf_glob = args.opperf or os.path.join(_REPO, "OPPERF_r*.jsonl")
    bench_paths = sorted(glob.glob(bench_glob))
    opperf_paths = sorted(glob.glob(opperf_glob))
    if not bench_paths and not opperf_paths:
        print(f"benchdiff: no artifacts match {bench_glob!r} or "
              f"{opperf_glob!r}", file=sys.stderr)
        return 1

    bench = trace_verdicts(
        freshness_verdicts(
            zero_verdicts(
                generate_verdicts(
                    quantization_verdicts(
                        fleet_verdicts(
                            headline_verdicts(load_bench(bench_paths),
                                              args.threshold),
                            args.threshold),
                        args.threshold),
                    args.threshold),
                args.threshold),
            args.threshold),
        args.threshold)
    opperf = opperf_diff(load_opperf(opperf_paths), args.threshold)

    failures = []
    if bench:
        last = sorted(bench)[-1]
        if bench[last]["verdict"] == "regression":
            failures.append(f"headline {last}: {bench[last]['reason']}")
        # the fleet phase gates like the headline: only rounds after
        # it first shipped carry a fleet verdict at all
        if bench[last].get("fleet_verdict") == "regression":
            failures.append(
                f"fleet {last}: {bench[last]['fleet_reason']}")
        # quantization gates the same way (round 18)
        if bench[last].get("quant_verdict") == "regression":
            failures.append(
                f"quantization {last}: {bench[last]['quant_reason']}")
        # generative decode gates the same way (round 17)
        if bench[last].get("gen_verdict") == "regression":
            failures.append(
                f"generate {last}: {bench[last]['gen_reason']}")
        # the zero-stage collective/memory/step budgets too (ZeRO)
        if bench[last].get("zero_verdict") == "regression":
            failures.append(
                f"zero {last}: {bench[last]['zero_reason']}")
        # online-learning freshness gates the same way (round 18)
        if bench[last].get("fresh_verdict") == "regression":
            failures.append(
                f"freshness {last}: {bench[last]['fresh_reason']}")
        # distributed-tracing attribution + overhead budget (round 20)
        if bench[last].get("trace_verdict") == "regression":
            failures.append(
                f"trace {last}: {bench[last]['trace_reason']}")
    if opperf.get("regressions"):
        failures.append(
            f"opperf {opperf['last']}: {len(opperf['regressions'])} "
            f"op(s) slower than {1 + args.threshold:.2f}x")

    if args.as_json:
        print(json.dumps({"headline": bench, "opperf": opperf,
                          "threshold": args.threshold,
                          "failures": failures}))
    else:
        print(render(bench, opperf, args.threshold))
        if failures:
            print("\nREGRESSIONS:\n" + "\n".join(
                f"  {f}" for f in failures))

    if args.fail_on_regression and failures:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
