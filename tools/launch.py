#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py:29,71 — the
dmlc-core tracker driving ssh/mpi/yarn/sge/local process groups).

TPU-native: workers connect to each other through jax.distributed (a
gRPC coordinator on worker 0) instead of a ps-lite scheduler, so the
launcher only has to start N processes with the right DMLC_* env vars —
the same contract the reference bootstraps from
(docs distributed_training.md:262-276).

Local mode (the reference's `--launcher local`, used by CI to test
dist_sync without a cluster, ci/docker/runtime_functions.sh:1367-1374):

    python tools/launch.py -n 4 python train.py ...

--cpu forces the workers onto the CPU backend with a virtual device
each — the way to exercise multi-worker semantics on one host (the
driver's 8-device CPU mesh pattern).  ssh/mpi launchers for real pods
are intentionally thin wrappers users drive through their own schedulers.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main():
    ap = argparse.ArgumentParser(
        description="launch a local multi-worker mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local", choices=["local"])
    ap.add_argument("--cpu", action="store_true",
                    help="force workers onto the CPU backend (local "
                         "multi-process testing)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for workers")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    port = _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
        })
        if args.cpu:
            env["JAX_PLATFORMS"] = "cpu"
            # the accelerator plugin registers at interpreter start and
            # would pre-initialize the backend, breaking
            # jax.distributed.initialize in the workers
            env.pop("PALLAS_AXON_POOL_IPS", None)
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        procs.append(subprocess.Popen(args.command, env=env))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
