#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py:29,71 — the
dmlc-core tracker driving ssh/mpi/yarn/sge/local process groups).

TPU-native: workers connect to each other through jax.distributed (a
gRPC coordinator on worker 0) instead of a ps-lite scheduler, so the
launcher only has to start N processes with the right DMLC_* env vars —
the same contract the reference bootstraps from
(docs distributed_training.md:262-276).

Modes:

  local  (reference `--launcher local`, used by CI to test dist_sync
          without a cluster, ci/docker/runtime_functions.sh:1367-1374)

      python tools/launch.py -n 4 python train.py ...

  ssh    (reference `--launcher ssh -H hostfile`): one worker per
          hostfile line, launched over ssh with the DMLC_* env inlined;
          worker 0's host is the jax.distributed coordinator.

      python tools/launch.py -n 4 --launcher ssh -H hosts.txt \\
          python train.py ...

  mpi    (reference `--launcher mpi`): delegates process placement to
          mpirun; ranks read OMPI_COMM_WORLD_RANK/PMI_RANK for their
          DMLC_WORKER_ID.

--cpu forces the workers onto the CPU backend with a virtual device
each — the way to exercise multi-worker semantics on one host (the
driver's 8-device CPU mesh pattern).

Failure handling (reference floor: kvstore get_num_dead_node,
include/mxnet/kvstore.h:380):

  * ``DistKVStore.num_dead_node(timeout_sec=...)`` reports workers
    whose parameter-server heartbeat went stale — poll it from rank 0
    to detect hung/dead peers.
  * ``--max-restarts K`` (local mode) relaunches a worker that exits
    nonzero, up to K times per rank.  This suits IDEMPOTENT worker
    scripts that re-initialize their own state (resume from a
    checkpoint, re-run a data shard).  It does NOT transparently
    resume an in-flight kvstore job: a crashed worker takes its
    parameter-server key shard's memory with it, and bulk-sync
    collectives cannot survive a lost member (jax.distributed tears
    the group down) — for training, recovery is a whole-job restart
    from the last checkpoint (Module.save_checkpoint / Trainer state
    files), the reference's recovery story too.  Use
    ``num_dead_node`` to DETECT the failure promptly; use
    checkpoints to recover.
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(args, rank, root_uri, port):
    env = {
        "DMLC_ROLE": "worker",
        "DMLC_PS_ROOT_URI": root_uri,
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_NUM_SERVER": "0",
    }
    if rank is not None:
        env["DMLC_WORKER_ID"] = str(rank)
    if args.cpu:
        env["JAX_PLATFORMS"] = "cpu"
    for kv in args.env:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


def _launch_local(args):
    port = _free_port()

    def spawn(rank):
        env = dict(os.environ)
        env.update(_worker_env(args, rank, "127.0.0.1", port))
        if args.cpu:
            # the accelerator plugin registers at interpreter start and
            # would pre-initialize the backend, breaking
            # jax.distributed.initialize in the workers
            env.pop("PALLAS_AXON_POOL_IPS", None)
        return subprocess.Popen(args.command, env=env)

    procs = [spawn(r) for r in range(args.num_workers)]
    if not args.max_restarts:
        return procs
    # supervise: relaunch nonzero-exit workers up to --max-restarts
    # times per rank (see module docstring for the dist_sync caveat)
    budget = [args.max_restarts] * args.num_workers
    while True:
        live = [p for p in procs if p.poll() is None]
        done = [(r, p) for r, p in enumerate(procs)
                if p.poll() is not None]
        restarted = False
        for r, p in done:
            if p.returncode and budget[r] > 0:
                budget[r] -= 1
                sys.stderr.write(
                    f"[launch] worker {r} exited rc={p.returncode}; "
                    f"restarting ({budget[r]} retries left)\n")
                procs[r] = spawn(r)
                restarted = True
        if not live and not restarted:
            return procs
        import time as _time

        _time.sleep(0.5)


def _launch_ssh(args):
    """Reference ssh_submit (dmlc_tracker/ssh.py): one worker per
    hostfile line; env is inlined into the remote command."""
    if not args.hostfile:
        raise SystemExit("--launcher ssh requires -H/--hostfile")
    with open(args.hostfile) as f:
        hosts = [h for h in (ln.strip() for ln in f)
                 if h and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        raise SystemExit(
            f"hostfile has {len(hosts)} hosts < -n {args.num_workers}")
    root_uri = hosts[0].split(":")[0]
    port = args.port or 9099
    procs = []
    for rank in range(args.num_workers):
        host, _, ssh_port = hosts[rank].partition(":")
        env = _worker_env(args, rank, root_uri, port)
        env_str = " ".join(f"{k}={shlex.quote(v)}"
                           for k, v in env.items())
        unset = "-u PALLAS_AXON_POOL_IPS " if args.cpu else ""
        remote = (f"cd {shlex.quote(args.workdir or '.')} && "
                  f"env {unset}{env_str} "
                  + " ".join(shlex.quote(c) for c in args.command))
        ssh_cmd = [args.ssh_cmd, "-o", "StrictHostKeyChecking=no"]
        if ssh_port:
            ssh_cmd += ["-p", ssh_port]
        procs.append(subprocess.Popen(ssh_cmd + [host, remote]))
    return procs


def _launch_mpi(args):
    """Reference mpi_submit: mpirun owns placement; each rank derives
    DMLC_WORKER_ID from its MPI rank env — kvstore.init_distributed
    falls back to OMPI_COMM_WORLD_RANK/PMI_RANK when DMLC_WORKER_ID is
    absent, so no per-rank env is needed here."""
    root_uri = args.root_uri or "127.0.0.1"
    port = args.port or 9099
    env = _worker_env(args, None, root_uri, port)
    flags = []
    for k, v in env.items():
        flags += ["-x", f"{k}={v}"]
    inner = list(args.command)
    if args.cpu:
        # same accelerator-plugin guard as the local/ssh paths
        inner = ["env", "-u", "PALLAS_AXON_POOL_IPS"] + inner
    cmd = ([args.mpirun_cmd, "-n", str(args.num_workers)] + flags
           + ["--allow-run-as-root"] + inner)
    return [subprocess.Popen(cmd)]


def main():
    ap = argparse.ArgumentParser(
        description="launch a multi-worker mxnet_tpu job")
    ap.add_argument("-n", "--num-workers", type=int, required=True)
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "mpi"])
    ap.add_argument("-H", "--hostfile", default=None,
                    help="ssh mode: one host[:port] per line")
    ap.add_argument("--ssh-cmd", default="ssh",
                    help="ssh binary (tests substitute a shim)")
    ap.add_argument("--mpirun-cmd", default="mpirun")
    ap.add_argument("--root-uri", default=None,
                    help="coordinator address override")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--workdir", default=None,
                    help="ssh mode: remote working directory")
    ap.add_argument("--cpu", action="store_true",
                    help="force workers onto the CPU backend (local "
                         "multi-process testing)")
    ap.add_argument("--env", action="append", default=[],
                    help="extra KEY=VALUE for workers")
    ap.add_argument("--max-restarts", type=int, default=0,
                    help="local mode: relaunch a nonzero-exit worker "
                         "up to K times (for idempotent/checkpoint-"
                         "resuming scripts; see docstring)")
    ap.add_argument("command", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    if not args.command:
        ap.error("no command given")

    launcher = {"local": _launch_local, "ssh": _launch_ssh,
                "mpi": _launch_mpi}[args.launcher]
    procs = launcher(args)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    main()
