#!/usr/bin/env python
"""Communication/transfer bandwidth measurement (reference:
tools/bandwidth/measure.py — kvstore push/pull bandwidth).

Measures host->device transfer, device->host readback, kvstore
push+pull, and (on a multi-device mesh) allreduce bandwidth.

    python tools/bandwidth.py [--size-mb 64]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402


def _time(fn, runs=10):
    fn()  # warmup
    t0 = time.perf_counter()
    for _ in range(runs):
        fn()
    return (time.perf_counter() - t0) / runs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64)
    ap.add_argument("--runs", type=int, default=10)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    nbytes = int(args.size_mb * 1e6)
    host = onp.random.rand(nbytes // 4).astype("float32")
    dev = jax.local_devices()[0]

    def h2d():
        jax.device_put(host, dev).block_until_ready()

    dt = _time(h2d, args.runs)
    print(json.dumps({"metric": "host_to_device",
                      "GBps": round(nbytes / dt / 1e9, 3)}))

    darr = jax.device_put(host, dev)

    def d2h():
        onp.asarray(darr)

    dt = _time(d2h, args.runs)
    print(json.dumps({"metric": "device_to_host",
                      "GBps": round(nbytes / dt / 1e9, 3)}))

    kv = mx.kv.create("device")
    val = mx.nd.array(host[: (len(host) // 1024) * 1024].reshape(-1, 1024), ctx=mx.gpu(0))
    kv.init("b", val)

    def pushpull():
        kv.push("b", val)
        out = mx.nd.zeros(val.shape, ctx=mx.gpu(0))
        kv.pull("b", out=out)
        out.wait_to_read()

    dt = _time(pushpull, args.runs)
    print(json.dumps({"metric": "kvstore_pushpull",
                      "GBps": round(2 * nbytes / dt / 1e9, 3)}))

    # wire-size accounting with 2-bit gradient compression: the packed
    # payload is what a dist push transmits (kvstore.py _reduce)
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvc.init("c", val)
    kvc.push("c", val)
    print(json.dumps({
        "metric": "push_wire_bytes",
        "uncompressed": kvc.last_uncompressed_bytes,
        "compressed_2bit": kvc.last_wire_bytes,
        "reduction_x": round(kvc.last_uncompressed_bytes
                             / max(kvc.last_wire_bytes, 1), 1)}))

    devs = jax.local_devices()
    if len(devs) > 1:
        from mxnet_tpu.parallel import get_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = get_mesh((len(devs),), ("d",), devices=devs)
        sharded = jax.device_put(
            jnp.asarray(host), NamedSharding(mesh, P("d")))
        # cross-shard reduce + broadcast back to every shard — the
        # all-reduce the kvstore's gradient sync performs
        allred = jax.jit(lambda x: x.sum() + 0 * x,
                         in_shardings=NamedSharding(mesh, P("d")),
                         out_shardings=NamedSharding(mesh, P("d")))

        def reduce_fn():
            jax.block_until_ready(allred(sharded))

        dt = _time(reduce_fn, args.runs)
        print(json.dumps({"metric": f"mesh_reduce_x{len(devs)}",
                          "GBps": round(nbytes / dt / 1e9, 3)}))


if __name__ == "__main__":
    main()
