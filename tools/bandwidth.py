#!/usr/bin/env python
"""Communication/transfer bandwidth measurement (reference:
tools/bandwidth/measure.py — kvstore push/pull bandwidth).

Measures host->device transfer, device->host readback, kvstore
push+pull, and (on a multi-device mesh) allreduce bandwidth.

    python tools/bandwidth.py [--size-mb 64]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402


def _time(fn, runs=10):
    fn()  # warmup
    t0 = time.perf_counter()
    for _ in range(runs):
        fn()
    return (time.perf_counter() - t0) / runs


def scaling_model():
    """The >=90%-at-256-chips argument (BASELINE north star; reference
    anchor example/image-classification/README.md:307-319 reports 90.1%
    at 256 GPUs over ethernet + dist_device_sync).

    Model: data-parallel ResNet-50, bs128/chip.  Per-step wire cost is
    the gradient allreduce; on a bidirectional ring (the ICI torus
    degenerate case — real 2D/3D tori only do better),
    t_comm = 2*(N-1)/N * G / B with G = grad bytes and B = per-chip
    allreduce bandwidth.  XLA overlaps the allreduce with the backward
    (grads for layer k are ready while k-1 still computes), so the
    exposed time is max(0, t_comm - overlap_window).  Efficiency =
    t_step / (t_step + exposed).

    Anchors: t_step = 44.9 ms measured on the chip (BENCH_r04/r05,
    device-chained); G = 102.2 MB (25.56M fp32 grads; the fused step
    all-reduces fp32 master grads — dryrun_collectives confirms the
    per-step collective bytes scale with exactly this term); the
    backward is ~60% of the step (XPlane r05: bwd convs 26.5 of
    44.9 ms), giving a 26.9 ms overlap window.

    B sweep: 45 GB/s is one v5e ICI link direction; a 2D torus axis
    gives ~90; 25 is a pessimistic DCN-limited figure (multi-pod
    slice where the reduce crosses data-center network).  Even at
    25 GB/s the exposed time is 0 — the window covers t_comm by 3x —
    so the efficiency bound is >=99% at every N; the reference's 90.1%
    anchor is cleared with an order of magnitude of slack.  The real
    risk at 256 chips is stragglers/jitter, not bandwidth — which the
    elastic heartbeat + supervised relaunch path (kvstore num_dead_node,
    tools/launch.py --max-restarts) addresses.
    """
    t_step = 44.9e-3
    grad_bytes = 25.56e6 * 4
    overlap = 0.6 * t_step
    rows = []
    for n in (8, 64, 256):
        for bw in (25e9, 45e9, 90e9):
            t_comm = 2 * (n - 1) / n * grad_bytes / bw
            exposed = max(0.0, t_comm - overlap)
            eff = t_step / (t_step + exposed)
            rows.append({"chips": n, "allreduce_GBps": bw / 1e9,
                         "t_comm_ms": round(t_comm * 1e3, 2),
                         "exposed_ms": round(exposed * 1e3, 2),
                         "efficiency": round(eff, 4)})
    print(json.dumps({
        "metric": "scaling_model_resnet50_bs128",
        "anchors": {"t_step_ms": 44.9, "grad_MB": 102.2,
                    "overlap_window_ms": 26.9,
                    "target": ">=0.90 efficiency at 256 chips "
                              "(example/image-classification/"
                              "README.md:307-319)"},
        "rows": rows,
        "argument": scaling_model.__doc__.strip(),
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64)
    ap.add_argument("--runs", type=int, default=10)
    ap.add_argument("--scaling-model", action="store_true",
                    help="emit the 256-chip scaling-efficiency model "
                         "row and exit (no device needed)")
    args = ap.parse_args()

    if args.scaling_model:
        scaling_model()
        return

    import jax
    import jax.numpy as jnp

    nbytes = int(args.size_mb * 1e6)
    host = onp.random.rand(nbytes // 4).astype("float32")
    dev = jax.local_devices()[0]

    def h2d():
        jax.device_put(host, dev).block_until_ready()

    dt = _time(h2d, args.runs)
    print(json.dumps({"metric": "host_to_device",
                      "GBps": round(nbytes / dt / 1e9, 3)}))

    darr = jax.device_put(host, dev)

    def d2h():
        onp.asarray(darr)

    dt = _time(d2h, args.runs)
    print(json.dumps({"metric": "device_to_host",
                      "GBps": round(nbytes / dt / 1e9, 3)}))

    kv = mx.kv.create("device")
    val = mx.nd.array(host[: (len(host) // 1024) * 1024].reshape(-1, 1024), ctx=mx.gpu(0))
    kv.init("b", val)

    def pushpull():
        kv.push("b", val)
        out = mx.nd.zeros(val.shape, ctx=mx.gpu(0))
        kv.pull("b", out=out)
        out.wait_to_read()

    dt = _time(pushpull, args.runs)
    print(json.dumps({"metric": "kvstore_pushpull",
                      "GBps": round(2 * nbytes / dt / 1e9, 3)}))

    # wire-size accounting with 2-bit gradient compression: the packed
    # payload is what a dist push transmits (kvstore.py _reduce)
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvc.init("c", val)
    kvc.push("c", val)
    print(json.dumps({
        "metric": "push_wire_bytes",
        "uncompressed": kvc.last_uncompressed_bytes,
        "compressed_2bit": kvc.last_wire_bytes,
        "reduction_x": round(kvc.last_uncompressed_bytes
                             / max(kvc.last_wire_bytes, 1), 1)}))

    devs = jax.local_devices()
    if len(devs) > 1:
        from mxnet_tpu.parallel import get_mesh
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = get_mesh((len(devs),), ("d",), devices=devs)
        sharded = jax.device_put(
            jnp.asarray(host), NamedSharding(mesh, P("d")))
        # cross-shard reduce + broadcast back to every shard — the
        # all-reduce the kvstore's gradient sync performs
        allred = jax.jit(lambda x: x.sum() + 0 * x,
                         in_shardings=NamedSharding(mesh, P("d")),
                         out_shardings=NamedSharding(mesh, P("d")))

        def reduce_fn():
            jax.block_until_ready(allred(sharded))

        dt = _time(reduce_fn, args.runs)
        print(json.dumps({"metric": f"mesh_reduce_x{len(devs)}",
                          "GBps": round(nbytes / dt / 1e9, 3)}))


if __name__ == "__main__":
    main()
