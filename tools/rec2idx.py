#!/usr/bin/env python
"""Regenerate the .idx file for an existing RecordIO .rec file.

Reference parity: tools/rec2idx.py (IndexCreator) — walks the record
stream, recording each record's byte offset keyed by its sequential
index, so ImageRecordIter/MXIndexedRecordIO can seek randomly into a
.rec produced without an index (or whose index was lost).

    python tools/rec2idx.py data.rec [data.idx]
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402


def make_index(rec_path, idx_path):
    reader = recordio.MXRecordIO(rec_path, "r")
    counter = 0
    try:
        with open(idx_path, "w") as idx:
            while True:
                pos = reader.tell()
                if reader.read() is None:
                    break
                idx.write(f"{counter}\t{pos}\n")
                counter += 1
    finally:
        reader.close()
    return counter


def main():
    ap = argparse.ArgumentParser(
        description="create an index file for a RecordIO file")
    ap.add_argument("record", help="path of the .rec file")
    ap.add_argument("index", nargs="?", default=None,
                    help="output .idx path (default: alongside .rec)")
    args = ap.parse_args()
    idx = args.index or os.path.splitext(args.record)[0] + ".idx"
    n = make_index(args.record, idx)
    print(f"wrote {n} entries to {idx}")


if __name__ == "__main__":
    main()
