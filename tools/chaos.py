#!/usr/bin/env python
"""Seeded chaos campaign: reproducible fault schedules over short
training runs, with the self-healing invariants asserted after every
one.

The reference framework has no fault-injection harness at all; this
repo's ``MXNET_FAULT_SPEC`` registry (PR 8) made single faults
deterministic program points.  The campaign composes them into a
SCHEDULE: ``--seed`` fixes every parameter (which scenario, which hit
count, when the external kill lands), ``--runs`` sets the volume, and
after each run three invariants must hold:

1. **no hangs** — the supervised run exits inside its deadline (a
   wedged survivor or a leaked non-daemon thread is a failure);
2. **no torn artifacts** — ``tools/ckpt_fsck.py --all`` walks every
   checkpoint version written during the run and every one must
   verify (stray ``.tmp.*`` files are allowed: they are the proof a
   mid-write death never reached the real artifact);
3. **healed == uninterrupted** — the run's final parameters (after
   any supervisor relaunch + resume) match the fault-free reference
   run ``allclose(1e-5)``.

Scenarios (round-robin over the schedule):

================  ====================================================
``sigkill``       the campaign SIGKILLs the victim process (pidfile)
                  at a seeded delay — uncooperative death anywhere,
                  mid-step and mid-checkpoint-write included; the
                  healing supervisor relaunches and the resume
                  continues from the newest good version
``sigterm_drain`` a seeded-delay SIGTERM: cooperative drain
                  checkpoint, rc -15, supervisor relaunch, resume
``peer_death``    a ghost peer's heartbeat goes stale mid-run: the
                  failure detector declares it dead, the emergency
                  checkpoint flushes from the freshest snapshot, the
                  survivor heal-exits (rc 83) and the relaunch
                  resumes
``heartbeat_delay``  ``peer.heartbeat:delay=...`` faults stall this
                  rank's own beats — absorbed, the run completes
``ckpt_async_crash``  ``ckpt.async:crash@K``: the process dies
                  mid-payload inside the ASYNC snapshot writer;
                  latest must stay previous-good, fsck clean
``ckpt_write_crash``  same for the synchronous writer (``ckpt.write``)
``collective_delay``  ``dist.collective:delay`` inside the dp(2)
                  sharded exchange — absorbed, the run completes
``record_corrupt``  the training shard is a .rec with 3 seeded-
                  corrupt records (torn frame / unpackable header /
                  undecodable payload) fed through the
                  MXNET_IO_WORKERS=4 pool: every corruption is
                  QUARANTINED (run-log counter evidence), the run
                  completes, and the final params match a
                  single-producer reference over the same corpus —
                  worker count and corruption perturb nothing
``io_worker_kill``  ``io.worker:crash@K`` kills a decode worker
                  thread mid-epoch (the pool's SIGKILL analog): the
                  batch it held is re-dispatched, the pool respawns
                  (run-log counter evidence), params still match the
                  reference
``zero3_peer_death``  the ghost-peer death lands mid-run in a ZeRO
                  STAGE-3 step (params live as flat bucket shards;
                  Module.fit cannot drive it, so the worker runs
                  make_train_step(zero_stage=3) directly on the dp(2)
                  mesh): the survivor flushes an emergency PARAMETER-
                  SHARD checkpoint — host-gathered through
                  stage3_save_params into the legacy named layout,
                  stamped sharding="zero3" + plan fingerprint — heal-
                  exits rc 83, and the relaunch verifies the
                  fingerprint, re-shards via stage3_load_params and
                  finishes shard-exact vs the reference
``decode_fault``  ``serve.decode:raise@K`` kills generative decode
                  steps mid-campaign (round 17): the breaker trips at
                  the consecutive-failure limit, every in-flight
                  sequence is shed ``ServeRejected(model_error)``,
                  EVERY page returns to the pool (the no-leak
                  invariant), and after the fault window drains the
                  SAME server recovers — the final fault-free
                  generation must match the fault-free reference
                  token-for-token
``trainer_death_midstream``  ``online.step:crash@K`` kills the online
                  trainer (round 18) between export boundaries, after
                  at least one stamped artifact was published: the
                  healing supervisor relaunches, the cursor-bearing
                  checkpoint resumes SAMPLE-EXACT (final params match
                  the fault-free reference), every published manifest
                  still points at a live stamp-matching artifact, and
                  the published version sequence stays strictly
                  increasing across the death
``swap_rollback``  a seeded ``serve.model:raise`` window is armed in
                  ONE fleet replica so its post-swap warm probe fails
                  mid-rollout: the swap must abort, roll every
                  already-cut-over replica back, leave the fleet on
                  ONE artifact identity (run-log counter evidence),
                  and the retried swap after the window drains must
                  commit everywhere with the reference's prediction
================  ====================================================

Usage::

    python tools/chaos.py --seed 1234 --runs 20 --out /tmp/chaos
    python tools/chaos.py --seed 7 --runs 7 --epochs 2   # quick

Prints one JSON summary line last; exit 0 iff every invariant held.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

SCENARIOS = ("sigkill", "sigterm_drain", "peer_death",
             "heartbeat_delay", "ckpt_async_crash", "ckpt_write_crash",
             "collective_delay", "record_corrupt", "io_worker_kill",
             "zero3_peer_death", "decode_fault",
             "trainer_death_midstream", "swap_rollback")

#: scenarios that intentionally kill the victim (a relaunch+resume is
#: expected); the others must complete on attempt 0
_LETHAL = {"sigkill", "sigterm_drain", "peer_death",
           "ckpt_async_crash", "ckpt_write_crash", "zero3_peer_death",
           "trainer_death_midstream"}


# ======================================================= worker half
def _build_rec_corpus(path, n=32):
    """A deterministic .rec shard with 3 seeded-bad records (torn
    frame / unpackable header / undecodable payload) via the SHARED
    recipe in ``mxnet_tpu.test_utils``.  Every attempt AND the
    reference build byte-identical corpora, so the surviving stream —
    and therefore the final params — must match regardless of worker
    count or worker faults."""
    from mxnet_tpu.test_utils import corrupt_rec, write_rec_corpus

    offsets = write_rec_corpus(path, n=n, labels=lambda i: i % 4)
    corrupt_rec(path, offsets, torn=[6], unpack=[13], decode=[22])
    return path


def _worker_zero3(args, attempt):
    """The ZeRO stage-3 arm: the live params are flat bucket shards
    (``make_train_step(zero_stage=3)``), which ``Module.fit`` cannot
    drive, so the training loop is explicit.  Attempt 0 arms healing
    against a fake 2-rank world, plants a live ghost beat, backdates
    it at the scheduled step, and the PeerDeadError at the next
    step-boundary poll flushes an emergency PARAMETER-SHARD
    checkpoint (host-gathered via ``stage3_save_params``, stamped
    ``sharding="zero3"``) before heal-exiting rc 83.  The relaunch
    refuses a fingerprint mismatch (``reshard_verdict``), re-shards
    via ``stage3_load_params`` and must finish shard-exact."""
    import pickle

    import jax
    import jax.numpy as jnp
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import get_mesh, make_train_step
    from mxnet_tpu.resilience import healing
    from mxnet_tpu.resilience.checkpoint import (
        CheckpointManager, stage3_load_params, stage3_save_params)
    from mxnet_tpu.resilience.elastic import (
        host_gather, reshard_verdict, topology_block)

    mx.random.seed(11)
    onp.random.seed(11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    net(mx.nd.zeros((1, 10)))

    mesh = get_mesh((2,), ("data",))
    step, params, opt_state = make_train_step(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), optimizer="adam",
        learning_rate=0.05, mesh=mesh, donate=False, autotune=False,
        optimizer_sharding="ps", zero_stage=3, bucket_bound=200)
    plan = step.zero_plan
    topo = topology_block(mesh=mesh, sharding="zero3", plan=plan,
                          zero_stage=3)

    rng = onp.random.RandomState(7)
    X = rng.randn(64, 10).astype("float32")
    y = (X @ rng.randn(10, 4)).argmax(axis=1).astype("float32")
    batches = [(jnp.asarray(X[o:o + 8]), jnp.asarray(y[o:o + 8]))
               for o in range(0, 64, 8)]
    total = int(args.epochs) * len(batches)
    key = jax.random.key(3)

    def _save(mgr, done):
        # a fresh version id per save (the mid-epoch-drain rule: never
        # rewrite an existing version in place); `step` carries the
        # resume cursor
        ver = (mgr.latest_epoch() or 0) + 1
        mgr.save(ver, arg_params=stage3_save_params(plan, params),
                 optimizer_states=pickle.dumps(jax.tree_util.tree_map(
                     host_gather, opt_state)),
                 step=done, epoch=done, topology=topo)

    start = 0
    mgr = CheckpointManager(args.prefix) if args.prefix else None
    if attempt > 0 and mgr is not None \
            and mgr.latest_epoch() is not None:
        st = mgr.load()
        verdict = reshard_verdict(st["topology"], topo)
        if (st["topology"] or {}).get("sharding") != "zero3" \
                or verdict["reshard"]:
            raise RuntimeError(
                "zero3 resume refused: checkpoint topology "
                f"{st['topology']} does not match the live plan: "
                f"{verdict}")
        params = stage3_load_params(plan, st["arg_params"], mesh=mesh)
        opt_state = jax.tree_util.tree_map(
            jnp.asarray, pickle.loads(st["optimizer_states"]))
        start = int(st["step"])
        telemetry.heal("healed_resume", detail=f"step={start}",
                       attempt=attempt)

    ghost_at = int(os.environ.get("CHAOS_GHOST_AT_BATCH", "0"))
    hb_dir = f"{args.prefix}.hb" if args.prefix else None
    ghost = {"armed": False, "stale": False}

    def _ghost_tick(t):
        # same choreography as the fit-level peer_death scenario: arm
        # + plant a live foreign-host ghost at the first boundary,
        # keep it beating, backdate it past the timeout at the
        # scheduled step
        if not ghost["armed"]:
            ghost["armed"] = True
            healing.arm(hb_dir, rank=0, num_ranks=2, timeout=0.5)
            healing._write_beat(hb_dir, 1)
            _unhost(hb_dir)
        elif not ghost["stale"] and t + 1 >= ghost_at:
            ghost["stale"] = True
            path = healing._hb_path(hb_dir, 1)
            old = time.time() - 999.0
            os.utime(path, (old, old))
        elif not ghost["stale"]:
            healing._write_beat(hb_dir, 1)
            _unhost(hb_dir)

    def _unhost(hb_dir):
        path = healing._hb_path(hb_dir, 1)
        with open(path) as f:
            payload = json.load(f)
        payload["host"] = "chaos-ghost"
        with open(path, "w") as f:
            f.write(json.dumps(payload))

    done = start
    try:
        for t in range(start, total):
            if attempt == 0 and ghost_at > 0 and hb_dir:
                _ghost_tick(t)
            healing.poll(step=t)
            xb, yb = batches[t % len(batches)]
            _, params, opt_state = step(params, opt_state, xb, yb,
                                        key, float(t + 1))
            done = t + 1
            if mgr is not None and done % 5 == 0:
                _save(mgr, done)
    except healing.PeerDeadError as e:
        print(f"chaos-worker: peer death detected ({e}); flushing "
              "parameter shards and healing out", flush=True)
        telemetry.heal("peer_death", detail=str(e))
        if mgr is not None:
            _save(mgr, done)
        healing.heal_exit("peer_death")
    finally:
        healing.disarm()

    import threading

    telemetry.close()
    stray = [t.name for t in threading.enumerate()
             if t.is_alive() and not t.daemon
             and t is not threading.main_thread()]
    final = stage3_save_params(plan, params)
    print(json.dumps({
        "final": {k: onp.asarray(v).ravel().tolist()
                  for k, v in sorted(final.items())},
        "threads_ok": not stray, "stray_threads": stray,
        "attempt": attempt}), flush=True)
    return 0


def _worker_generate(args, attempt):
    """The generative-serving arm (round 17, ``decode_fault``): a
    warm-started GenerativeServer takes a burst of prompts while the
    seeded ``serve.decode:raise`` spec kills decode steps — the
    breaker must trip, in-flight sequences must shed
    ``ServeRejected(reason="model_error")`` and EVERY page must return
    to the pool (the no-leak invariant).  Then the faults are
    disarmed and the SAME server must recover: the final fault-free
    generation is the run's ``final`` payload, compared
    token-for-token against the fault-free reference."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.resilience import faultsim
    from mxnet_tpu.serving import GenerativeServer, ServeRejected

    spec = os.environ.get("MXNET_FAULT_SPEC", "")
    # warm start fault-free: the warm-up probe steps the decode
    # program too, and a spec hit-window indexed from process start
    # would land there instead of mid-campaign
    faultsim.reset("")
    srv = GenerativeServer(
        seed=0, vocab=32, prompt_buckets=(4, 8), max_new=6, slots=4,
        page_tokens=4, pool_budget=64 * 1024, kv_dtype="float32",
        breaker_limit=2, name="chaos-generate")
    srv.start(warm=True)
    if attempt == 0 and spec:
        faultsim.reset(spec)  # hit 1 = the campaign's first decode
    prompts = [[(7 * i + j) % srv.vocab for j in range(2 + i % 6)]
               for i in range(6)]
    problems = []
    final = {}
    try:
        # storm phase: the armed fault lands on the decode loop
        reasons = {}
        handles = []
        for p in prompts:
            try:
                handles.append(srv.submit(p))
            except ServeRejected as e:
                reasons[e.reason] = reasons.get(e.reason, 0) + 1
        for h in handles:
            try:
                h.result(timeout=60)
            except ServeRejected as e:
                reasons[e.reason] = reasons.get(e.reason, 0) + 1
        if attempt == 0 and spec:
            if srv.stats["breaker_trips"] < 1:
                problems.append(
                    "breaker never tripped under the armed decode "
                    "fault")
            if reasons.get("model_error", 0) < 1:
                problems.append(
                    "no in-flight sequence was shed ServeRejected"
                    f"(model_error); shed reasons: {reasons}")
        if srv.pool.pages_in_use != 0:
            problems.append(
                f"page leak: {srv.pool.pages_in_use} page(s) still "
                "held after the storm")
        # recovery phase: disarm, the SAME server must serve again
        faultsim.reset("")
        give_up = time.monotonic() + 30.0
        for i, p in enumerate(prompts):
            toks = None
            while toks is None and time.monotonic() < give_up:
                try:
                    toks = srv.submit(p).result(timeout=30)
                except ServeRejected:
                    time.sleep(0.02)  # breaker still re-warming
            if toks is None:
                problems.append(
                    f"no recovery: prompt {i} never served after the "
                    "faults were disarmed")
                break
            final[f"prompt{i}"] = [int(t) for t in toks]
    finally:
        srv.drain(timeout=10.0)
        srv.close()

    import threading

    telemetry.close()
    stray = [t.name for t in threading.enumerate()
             if t.is_alive() and not t.daemon
             and t is not threading.main_thread()]
    if problems:
        print("chaos-worker(generate): " + "; ".join(problems),
              file=sys.stderr, flush=True)
        return 1
    print(json.dumps({"final": final, "threads_ok": not stray,
                      "stray_threads": stray, "attempt": attempt}),
          flush=True)
    return 0


def _worker_online(args, attempt):
    """The online-learning arm (round 18, ``trainer_death_midstream``):
    the :class:`OnlineTrainer` consumes its deterministic replay
    stream, exporting a stamped ``.mxje`` every few steps, while the
    seeded ``online.step:crash`` spec kills the process mid-stream —
    after the first export, before the last.  The healing supervisor
    relaunches; the resume must be SAMPLE-EXACT (final params match
    the fault-free reference bit-for-bit), every published manifest
    must point at a live artifact whose stamp agrees (no torn
    publishes), and the version sequence must stay strictly
    increasing across the death."""
    from mxnet_tpu import deploy, telemetry
    from mxnet_tpu.online import OnlineTrainer
    from mxnet_tpu.resilience import faultsim

    if attempt > 0:
        faultsim.reset("")
    workdir = (f"{args.prefix}.online" if args.prefix
               else tempfile.mkdtemp(prefix="chaos_online_"))
    tr = OnlineTrainer(workdir, steps=12, export_every=4, seed=5)
    if args.pidfile and attempt == 0:
        with open(args.pidfile, "w") as f:
            f.write(str(os.getpid()))
    final = tr.run()  # attempt 0 may os._exit(87) mid-stream here

    problems = []
    versions = []
    for name in sorted(os.listdir(tr.publish_dir)):
        if not (name.startswith("v") and name.endswith(".json")):
            continue
        with open(os.path.join(tr.publish_dir, name)) as f:
            man = json.load(f)
        versions.append(int(man["model_version"]))
        try:
            meta = deploy.read_artifact_meta(man["path"])
        except Exception as e:
            problems.append(f"manifest {name} points at an unreadable "
                            f"artifact: {e}")
            continue
        if int(meta.get("model_version", -1)) != versions[-1]:
            problems.append(
                f"manifest {name} stamp mismatch: artifact says "
                f"{meta.get('model_version')}")
    if not versions:
        problems.append("no artifact was ever published")
    elif versions != sorted(set(versions)):
        problems.append(
            f"published versions not strictly increasing: {versions}")

    import threading

    telemetry.close()
    stray = [t.name for t in threading.enumerate()
             if t.is_alive() and not t.daemon
             and t is not threading.main_thread()]
    if problems:
        print("chaos-worker(online): " + "; ".join(problems),
              file=sys.stderr, flush=True)
        return 1
    print(json.dumps({"final": final["params"],
                      "threads_ok": not stray, "stray_threads": stray,
                      "attempt": attempt}), flush=True)
    return 0


def _worker_swap(args, attempt):
    """The rolling-swap arm (round 18, ``swap_rollback``): a 2-replica
    fleet serves v1 and the seeded ``serve.model:raise`` window is
    armed in ONE replica's env, so its post-swap warm probe fails
    after its sibling already cut over — the rollout must abort, roll
    the cut-over replica back and leave the fleet on ONE artifact
    identity.  Once the window is consumed the retried swap must
    commit v2 everywhere, and the final routed prediction is the
    run's ``final`` payload, compared against the fault-free
    reference (which swaps cleanly first try)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import deploy, gluon, nd, telemetry
    from mxnet_tpu.resilience import faultsim
    from mxnet_tpu.serving import FleetRouter

    spec = os.environ.get("MXNET_FAULT_SPEC", "")
    # the spec targets a REPLICA's probe path, not this client process
    faultsim.reset("")
    workdir = (f"{args.prefix}.swap" if args.prefix
               else tempfile.mkdtemp(prefix="chaos_swap_"))
    os.makedirs(workdir, exist_ok=True)

    def _artifact(version, seed):
        net = gluon.nn.Dense(1, in_units=4,
                             prefix=f"chaos_swap{version}_")
        net.initialize(init=mx.init.Xavier())
        net(nd.zeros((1, 4)))
        rng = onp.random.RandomState(seed)
        net.weight.set_data(nd.array(rng.uniform(
            -0.5, 0.5, size=(1, 4)).astype("float32")))
        net.bias.set_data(nd.zeros((1,)))
        path = os.path.join(workdir, f"model-v{version}.mxje")
        deploy.export_model(net, nd.zeros((8, 4)), path,
                            platforms=("cpu",),
                            extra_meta={"model_version": version})
        return path

    v1, v2 = _artifact(1, 31), _artifact(2, 32)
    replica_env = ({1: {"MXNET_FAULT_SPEC": spec}}
                   if attempt == 0 and spec else None)
    problems = []
    final = {}
    router = FleetRouter.spawn(v1, replicas=2,
                               env={"JAX_PLATFORMS": "cpu"},
                               coalesce_ms=1.0,
                               replica_env=replica_env or {})
    try:
        first = router.rolling_swap(v2, probe_timeout=60.0)
        if replica_env:
            if first["committed"]:
                problems.append(
                    "armed probe fault but the rollout committed")
            elif not first["consistent"]:
                problems.append(
                    "fleet straddles two identities after rollback: "
                    f"{first['identities']}")
            elif set(first["identities"].values()) != {v1}:
                problems.append(
                    "rollback left the fleet off the previous "
                    f"artifact: {first['identities']}")
        res = first
        give_up = time.monotonic() + 30.0
        while not res["committed"] and time.monotonic() < give_up:
            time.sleep(0.1)
            res = router.rolling_swap(v2, probe_timeout=60.0)
        if not res["committed"]:
            problems.append(
                f"retried swap never committed: {res['errors']}")
        elif not res["consistent"] \
                or set(res["identities"].values()) != {v2}:
            problems.append(
                f"post-retry identities inconsistent: "
                f"{res['identities']}")
        out = router.submit(onp.ones((4,), dtype="float32"),
                            deadline_ms=10000)
        final = {"probe": onp.asarray(out, dtype="float64")
                 .ravel().tolist()}
    finally:
        router.close()

    import threading

    telemetry.close()
    stray = [t.name for t in threading.enumerate()
             if t.is_alive() and not t.daemon
             and t is not threading.main_thread()]
    if problems:
        print("chaos-worker(swap): " + "; ".join(problems),
              file=sys.stderr, flush=True)
        return 1
    print(json.dumps({"final": final, "threads_ok": not stray,
                      "stray_threads": stray, "attempt": attempt}),
          flush=True)
    return 0


def _worker(args):
    """One training run (the supervised command): attempt 0 arms the
    scenario's faults and may die; relaunch attempts scrub the faults
    and resume from the newest good checkpoint.  Deterministic model,
    data and seeds — every attempt and the reference consume the same
    stream."""
    attempt = int(os.environ.get("MXNET_HEAL_ATTEMPT", "0"))
    if args.prefix:
        os.environ["MXNET_RUNLOG"] = \
            f"{args.prefix}.runlog.a{attempt}.jsonl"
    if attempt > 0:
        os.environ.pop("MXNET_FAULT_SPEC", None)
        os.environ.pop("CHAOS_GHOST_AT_BATCH", None)
    if args.ctx == "zero3":
        return _worker_zero3(args, attempt)
    if args.ctx == "generate":
        return _worker_generate(args, attempt)
    if args.ctx == "online":
        return _worker_online(args, attempt)
    if args.ctx == "online_swap":
        return _worker_swap(args, attempt)

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.resilience import faultsim, healing
    from mxnet_tpu.resilience.checkpoint import CheckpointManager

    if attempt > 0:
        faultsim.reset("")

    mx.random.seed(11)
    onp.random.seed(11)
    if args.ctx == "rec":
        # the data-plane scenarios: train straight from a .rec shard
        # with seeded-corrupt records through the record pipeline
        rec_dir = tempfile.mkdtemp(prefix="chaos_rec_")
        rec_path = _build_rec_corpus(os.path.join(rec_dir, "train.rec"))
        it = mx.io.ImageRecordIter(
            path_imgrec=rec_path, data_shape=(3, 16, 16),
            batch_size=8, std_r=255.0, std_g=255.0, std_b=255.0)
        top = sym.Flatten(sym.Variable("data"))
    else:
        rng = onp.random.RandomState(7)
        X = rng.randn(64, 10).astype("float32")
        y = (X @ rng.randn(10, 4)).argmax(axis=1).astype("float32")
        it = mx.io.NDArrayIter(X, y, batch_size=8, shuffle=False)
        top = sym.Variable("data")

    fc1 = sym.FullyConnected(top, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(fc2, sym.Variable("softmax_label"),
                            name="softmax")

    if args.ctx == "dp2":
        context = [mx.gpu(i) for i in range(2)]
        kvstore = "dist_sync"
    else:
        context = mx.cpu()
        kvstore = "local"
    mod = mx.mod.Module(net, context=context)

    resume_from = None
    if attempt > 0 and args.prefix \
            and CheckpointManager(args.prefix).latest_epoch() \
            is not None:
        resume_from = args.prefix

    # ghost-peer injection (the peer_death scenario): at batch 1 arm
    # healing against a fake 2-rank world and plant a LIVE ghost beat;
    # at the scheduled batch, backdate it past MXNET_PEER_TIMEOUT_SEC
    # — the next step-boundary poll must declare the peer dead
    ghost_at = int(os.environ.get("CHAOS_GHOST_AT_BATCH", "0"))
    callbacks = []
    if attempt == 0 and os.environ.get("CHAOS_SELF_HEAL") \
            and args.prefix:
        # a 1-rank healing world: no peers to lose, but the heartbeat
        # thread runs for real — the peer.heartbeat delay faults land
        # on live beats and must be absorbed, not fatal
        healing.arm(f"{args.prefix}.hb", rank=0, num_ranks=1)

    # the external-kill scenarios need the kill to land MID-fit, not
    # mid-import: the pidfile (the campaign's kill trigger) is written
    # at the FIRST batch boundary, and CHAOS_PACE_S stretches the fit
    # so the seeded delay window stays inside it
    pace = float(os.environ.get("CHAOS_PACE_S", "0") or 0)
    pid_done = [attempt != 0 or not args.pidfile]

    def _pace(param):
        if not pid_done[0]:
            pid_done[0] = True
            with open(args.pidfile, "w") as f:
                f.write(str(os.getpid()))
        if pace:
            time.sleep(pace)

    callbacks.append(_pace)
    if attempt == 0 and ghost_at > 0 and args.prefix:
        hb_dir = f"{args.prefix}.hb"
        state = {"armed": False, "stale": False}

        def _ghost(param):
            if not state["armed"]:
                state["armed"] = True
                healing.arm(hb_dir, rank=0, num_ranks=2, timeout=0.5)
                healing._write_beat(hb_dir, 1)
                _unhost_ghost(hb_dir)
            elif not state["stale"] and param.nbatch + 1 >= ghost_at:
                state["stale"] = True
                path = healing._hb_path(hb_dir, 1)
                old = time.time() - 999.0
                os.utime(path, (old, old))
            elif not state["stale"]:
                healing._write_beat(hb_dir, 1)
                _unhost_ghost(hb_dir)

        def _unhost_ghost(hb_dir):
            # a foreign-host ghost: the detector must use staleness,
            # not the same-host pid probe (the recorded pid is ours)
            path = healing._hb_path(hb_dir, 1)
            with open(path) as f:
                payload = json.load(f)
            payload["host"] = "chaos-ghost"
            with open(path, "w") as f:
                f.write(json.dumps(payload))

        callbacks.append(_ghost)

    try:
        mod.fit(it, num_epoch=args.epochs,
                kvstore=kvstore, optimizer="adam",
                optimizer_params=(("learning_rate", 0.05),),
                initializer=mx.init.Xavier(),
                checkpoint=args.prefix or None,
                resume_from=resume_from,
                batch_end_callback=callbacks or None)
    except healing.PeerDeadError as e:
        print(f"chaos-worker: peer death detected ({e}); healing out",
              flush=True)
        healing.heal_exit("peer_death")
    finally:
        healing.disarm()
        if args.ctx == "rec":
            import shutil

            it.close()
            shutil.rmtree(rec_dir, ignore_errors=True)

    import threading

    from mxnet_tpu import telemetry

    telemetry.close()  # flush run_end + final counters
    stray = [t.name for t in threading.enumerate()
             if t.is_alive() and not t.daemon
             and t is not threading.main_thread()]
    arg_p, _ = mod.get_params()
    print(json.dumps({
        "final": {k: v.asnumpy().ravel().tolist()
                  for k, v in sorted(arg_p.items())},
        "threads_ok": not stray, "stray_threads": stray,
        "attempt": attempt}), flush=True)
    return 0


# ===================================================== campaign half
def _schedule(seed, runs, scenarios):
    """The seeded, reproducible fault schedule: same seed = same
    scenario order, hit counts and kill delays, run for run."""
    rng = random.Random(int(seed))
    plan = []
    for i in range(int(runs)):
        scen = scenarios[i % len(scenarios)]
        entry = {"run": i, "scenario": scen}
        if scen == "sigkill":
            entry["kill_delay_s"] = round(rng.uniform(0.2, 2.0), 3)
            entry["signal"] = int(signal.SIGKILL)
        elif scen == "sigterm_drain":
            entry["kill_delay_s"] = round(rng.uniform(0.2, 2.0), 3)
            entry["signal"] = int(signal.SIGTERM)
        elif scen == "peer_death":
            entry["ghost_at_batch"] = rng.randint(2, 6)
        elif scen == "zero3_peer_death":
            entry["ghost_at_batch"] = rng.randint(2, 6)
        elif scen == "heartbeat_delay":
            entry["self_heal"] = 1
            # window pinned to start at hit 1: inline beats are
            # rate-limited, so a short run may only beat a few times
            entry["fault_spec"] = (
                f"peer.heartbeat:delay="
                f"{round(rng.uniform(0.1, 0.4), 2)}"
                f"@1-{rng.randint(4, 8)}")
        elif scen == "ckpt_async_crash":
            entry["fault_spec"] = \
                f"ckpt.async:crash@{rng.randint(2, 8)}"
        elif scen == "ckpt_write_crash":
            entry["fault_spec"] = \
                f"ckpt.write:crash@{rng.randint(2, 6)}"
        elif scen == "collective_delay":
            entry["fault_spec"] = (
                f"dist.collective:delay="
                f"{round(rng.uniform(0.05, 0.3), 2)}"
                f"@{rng.randint(1, 6)}")
        elif scen == "record_corrupt":
            entry["io_workers"] = 4  # corruption IS the fault
        elif scen == "io_worker_kill":
            entry["io_workers"] = 4
            entry["fault_spec"] = \
                f"io.worker:crash@{rng.randint(2, 6)}"
        elif scen == "decode_fault":
            # the worker re-arms AFTER its warm start, so hit 1 is
            # the campaign's first decode step; breaker_limit is 2
            start = rng.randint(1, 3)
            entry["fault_spec"] = \
                f"serve.decode:raise@{start}-{start + 1}"
        elif scen == "trainer_death_midstream":
            # the online worker exports every 4 of 12 steps: a crash
            # in hits 5..11 always lands AFTER the first publish and
            # BEFORE the final export
            entry["fault_spec"] = \
                f"online.step:crash@{rng.randint(5, 11)}"
        elif scen == "swap_rollback":
            # armed in ONE replica's env; hit 1 is its post-swap warm
            # probe and the server retries FaultInjected 3x per
            # batch, so the window must span all 3 attempts — hits
            # past it stay clean for the retried swap
            entry["fault_spec"] = \
                f"serve.model:raise@1-{rng.randint(3, 4)}"
        plan.append(entry)
    return plan


def _worker_env(base, entry, prefix):
    env = dict(base)
    env.pop("MXNET_FAULT_SPEC", None)
    env.pop("CHAOS_GHOST_AT_BATCH", None)
    if entry.get("fault_spec"):
        env["MXNET_FAULT_SPEC"] = entry["fault_spec"]
    env.pop("CHAOS_SELF_HEAL", None)
    if entry.get("ghost_at_batch"):
        env["CHAOS_GHOST_AT_BATCH"] = str(entry["ghost_at_batch"])
        env["MXNET_PEER_TIMEOUT_SEC"] = "0.5"
    if entry.get("self_heal"):
        env["CHAOS_SELF_HEAL"] = "1"
    if entry.get("io_workers"):
        env["MXNET_IO_WORKERS"] = str(entry["io_workers"])
    if "kill_delay_s" in entry:
        # stretch the fit past the kill window so the seeded delay
        # lands mid-run (mid-step, mid-epoch-boundary, mid-ckpt-write)
        env["CHAOS_PACE_S"] = "0.15"
    env["MXNET_SNAPSHOT_EVERY"] = "3"
    return env


def _kill_when_ready(pidfile, delay, sig, result, deadline=60.0):
    """The external executioner: wait for the victim's pidfile, sleep
    the SEEDED delay, deliver the signal.  A victim that already
    finished is left in peace.  ``result['delivered']`` records
    whether the signal actually landed — the campaign's fault count
    must not claim kills that out-raced the run."""
    t0 = time.monotonic()
    while not os.path.exists(pidfile):
        if time.monotonic() - t0 > deadline:
            return
        time.sleep(0.05)
    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return
    time.sleep(delay)
    try:
        os.kill(pid, sig)
        result["delivered"] = True
    except (ProcessLookupError, PermissionError):
        pass  # already gone: the schedule out-raced the run


def _ctx_for(entry):
    if entry["scenario"] == "collective_delay":
        return "dp2"
    if entry["scenario"] in ("record_corrupt", "io_worker_kill"):
        return "rec"  # reference: same corrupt corpus, 0 workers
    if entry["scenario"] == "zero3_peer_death":
        return "zero3"  # reference: same loop, no ghost, no faults
    if entry["scenario"] == "decode_fault":
        return "generate"  # reference: same campaign, no faults
    if entry["scenario"] == "trainer_death_midstream":
        return "online"  # reference: same stream, no crash
    if entry["scenario"] == "swap_rollback":
        return "online_swap"  # reference: clean first-try swap
    return "cpu"


def _run_reference(ctx, outdir, env):
    ref_prefix = os.path.join(outdir, f"reference-{ctx}")
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--ctx", ctx, "--epochs", str(env["_CHAOS_EPOCHS"])],
        env={k: v for k, v in env.items() if not k.startswith("_")},
        capture_output=True, text=True, timeout=240)
    if r.returncode != 0:
        raise RuntimeError(
            f"reference run ({ctx}) failed rc={r.returncode}:\n"
            + r.stderr[-2000:])
    out = json.loads(r.stdout.strip().splitlines()[-1])
    with open(ref_prefix + ".json", "w") as f:
        f.write(json.dumps(out["final"]))
    return out["final"]


def campaign(args):
    import threading

    import numpy as onp

    outdir = args.out or tempfile.mkdtemp(prefix="mxnet_tpu_chaos_")
    os.makedirs(outdir, exist_ok=True)
    scenarios = tuple(args.scenarios.split(",")) if args.scenarios \
        else SCENARIOS
    unknown = set(scenarios) - set(SCENARIOS)
    if unknown:
        raise SystemExit(f"unknown scenario(s) {sorted(unknown)}; "
                         f"known: {list(SCENARIOS)}")
    plan = _schedule(args.seed, args.runs, scenarios)

    env = dict(os.environ)
    # scrub operator-level state that would poison the campaign: an
    # armed fault spec must not fire in the fault-free REFERENCE arm
    # (workers re-arm per scenario), a parent run log must not absorb
    # every child's telemetry (workers set their own per attempt),
    # and ambient healing must not arm where a scenario did not ask
    for k in ("MXNET_FAULT_SPEC", "MXNET_RUNLOG",
              "MXNET_METRICS_TEXTFILE", "MXNET_HEARTBEAT_DIR",
              "MXNET_SNAPSHOT_EVERY", "CHAOS_GHOST_AT_BATCH",
              "CHAOS_SELF_HEAL", "CHAOS_PACE_S", "MXNET_HEAL_ATTEMPT",
              "MXNET_IO_WORKERS"):
        env.pop(k, None)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = \
            flags + " --xla_force_host_platform_device_count=2"
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(outdir, "xla_cache"))
    # the in-step autotuner races numerically-inequivalent variants
    # (jnp vs pallas adam differ by ulps): pin it off so every arm of
    # every run compiles the identical program
    env["MXNET_AUTOTUNE"] = "0"
    env["_CHAOS_EPOCHS"] = str(args.epochs)

    print(f"chaos: seed={args.seed} runs={len(plan)} "
          f"scenarios={list(scenarios)} out={outdir}", flush=True)
    references = {}
    failures = []
    results = []
    faults_injected = 0
    from tools import ckpt_fsck

    for entry in plan:
        i = entry["run"]
        scen = entry["scenario"]
        ctx = _ctx_for(entry)
        if ctx not in references:
            references[ctx] = _run_reference(ctx, outdir, env)
        rundir = os.path.join(outdir, f"run{i:02d}")
        os.makedirs(rundir, exist_ok=True)
        prefix = os.path.join(rundir, "ck")
        pidfile = os.path.join(rundir, "victim.pid")
        run_env = _worker_env(env, entry, prefix)
        run_env = {k: v for k, v in run_env.items()
                   if not k.startswith("_")}
        cmd = [sys.executable, "-m", "mxnet_tpu.resilience.healing",
               "--relaunch", "--max-relaunch", "2", "--",
               sys.executable, os.path.abspath(__file__), "--worker",
               "--prefix", prefix, "--ctx", ctx,
               "--epochs", str(args.epochs), "--pidfile", pidfile]
        killer = None
        kill_result = {"delivered": False}
        if "kill_delay_s" in entry:
            killer = threading.Thread(
                target=_kill_when_ready,
                args=(pidfile, entry["kill_delay_s"],
                      entry["signal"], kill_result),
                daemon=True)
            killer.start()
        t0 = time.monotonic()
        problems = []
        try:
            r = subprocess.run(cmd, env=run_env, capture_output=True,
                               text=True, timeout=args.run_timeout)
        except subprocess.TimeoutExpired:
            problems.append(
                f"HANG: run exceeded {args.run_timeout}s")
            r = None
        wall = round(time.monotonic() - t0, 2)
        if killer is not None:
            killer.join(timeout=10)
        final = None
        if r is not None:
            if r.returncode != 0:
                problems.append(
                    f"supervised run exited rc={r.returncode}: "
                    + (r.stdout + r.stderr)[-800:])
            else:
                try:
                    last = [ln for ln in r.stdout.splitlines()
                            if ln.strip().startswith("{")][-1]
                    out = json.loads(last)
                    final = out["final"]
                    if not out.get("threads_ok", False):
                        problems.append(
                            "hung threads after fit: "
                            f"{out.get('stray_threads')}")
                except (IndexError, ValueError, KeyError) as e:
                    problems.append(
                        f"no final-params JSON from worker ({e}); "
                        f"tail: {r.stdout[-500:]}")
        # invariant 2: every artifact the run left behind verifies
        fsck_report = ckpt_fsck.fsck(rundir, check_all=True)
        if not fsck_report["clean"]:
            problems.append("torn artifacts: "
                            + "; ".join(fsck_report["problems"]))
        # deterministic-death scenarios MUST have died and relaunched
        # (a per-attempt run log proves the supervisor respawned);
        # peer_death additionally must show the heal chain in the
        # victim's log: a declared death and an emergency/fallback
        # checkpoint before the heal_exit
        relaunched = os.path.exists(f"{prefix}.runlog.a1.jsonl")
        if scen in ("peer_death", "zero3_peer_death",
                    "ckpt_async_crash", "ckpt_write_crash",
                    "trainer_death_midstream") and not relaunched:
            problems.append(
                "scenario guarantees a death but no relaunch run log "
                "exists — the fault never fired")
        if scen in ("peer_death", "zero3_peer_death") and relaunched:
            heals = []
            try:
                with open(f"{prefix}.runlog.a0.jsonl") as f:
                    heals = [json.loads(ln) for ln in f
                             if '"type": "heal"' in ln
                             or '"type":"heal"' in ln]
            except OSError:
                pass
            actions = {h.get("action") for h in heals}
            if "peer_death" not in actions:
                problems.append(
                    "victim run log carries no heal/peer_death "
                    f"record (heal actions: {sorted(actions)})")
        # invariant 3: healed == uninterrupted
        if final is not None:
            ref = references[ctx]
            for k in ref:
                if not onp.allclose(onp.asarray(final[k]),
                                    onp.asarray(ref[k]),
                                    rtol=1e-5, atol=1e-7):
                    problems.append(
                        f"final params diverge from reference at {k}")
                    break
        # HONEST fault accounting: count a fault only when it provably
        # landed — a delivered external signal, a relaunch forced by a
        # deterministic crash, or fault-counter evidence in the
        # victim's run log (the delay scenarios complete cleanly, so
        # their run_end counters survive).  A scheduled-but-undelivered
        # fault is a PROBLEM for the deterministic scenarios and a
        # benign miss for the timing-raced kills.
        fault_landed = False
        if "kill_delay_s" in entry:
            fault_landed = kill_result["delivered"] or relaunched
        elif scen in ("peer_death", "zero3_peer_death",
                      "ckpt_async_crash", "ckpt_write_crash",
                      "trainer_death_midstream"):
            fault_landed = relaunched
        elif scen in ("record_corrupt", "io_worker_kill"):
            # data-plane evidence: the victim's run_end counters must
            # show the quarantine (record_corrupt) or the worker
            # respawn (io_worker_kill) actually happened
            key = ("data_records_skipped" if scen == "record_corrupt"
                   else "io_worker_respawns")
            counters = {}
            try:
                with open(f"{prefix}.runlog.a0.jsonl") as f:
                    ends = [json.loads(ln) for ln in f
                            if '"type": "run_end"' in ln
                            or '"type":"run_end"' in ln]
                if ends:
                    counters = ends[-1].get("counters", {})
            except OSError:
                pass
            fault_landed = counters.get(key, 0) >= 1
            if not fault_landed:
                problems.append(
                    f"{scen}: run_end counter {key} shows zero — the "
                    "data-plane fault never landed")
            elif scen == "record_corrupt" \
                    and counters.get("data_records_skipped", 0) != 3:
                problems.append(
                    "record_corrupt: expected exactly 3 quarantined "
                    f"records, counters say "
                    f"{counters.get('data_records_skipped')}")
        elif scen == "swap_rollback":
            # the rollout runs IN the victim process: its run_end
            # counters must show the aborted+rolled-back swap
            counters = {}
            try:
                with open(f"{prefix}.runlog.a0.jsonl") as f:
                    ends = [json.loads(ln) for ln in f
                            if '"type": "run_end"' in ln
                            or '"type":"run_end"' in ln]
                if ends:
                    counters = ends[-1].get("counters", {})
            except OSError:
                pass
            fault_landed = \
                counters.get("fleet_swap_rollbacks", 0) >= 1
            if not fault_landed:
                problems.append(
                    "swap_rollback: run_end counter "
                    "fleet_swap_rollbacks shows zero — the probe "
                    "fault never forced a rollback")
        else:  # delay scenarios: the armed spec's hits are in the log
            try:
                with open(f"{prefix}.runlog.a0.jsonl") as f:
                    ends = [json.loads(ln) for ln in f
                            if '"type": "run_end"' in ln
                            or '"type":"run_end"' in ln]
                fault_landed = bool(ends) and \
                    ends[-1]["counters"].get("faults", 0) >= 1
            except OSError:
                fault_landed = False
            if not fault_landed:
                problems.append(
                    "delay fault spec armed but the victim run log "
                    "shows zero injected faults")
        if fault_landed:
            faults_injected += 1
        row = {"run": i, "scenario": scen, "wall_s": wall,
               "ok": not problems, "problems": problems,
               "relaunched": relaunched,
               "fault_landed": fault_landed,
               "schedule": {k: v for k, v in entry.items()
                            if k not in ("run", "scenario")}}
        results.append(row)
        status = "ok" if not problems else "FAIL"
        print(f"chaos run {i:02d} [{scen}] {status} ({wall}s)"
              + ("" if not problems else f" — {problems[0][:160]}"),
              flush=True)
        if problems:
            failures.append(row)
        elif not args.keep:
            import shutil

            shutil.rmtree(rundir, ignore_errors=True)

    fault_shortfall = faults_injected < int(args.min_faults)
    summary = {
        "seed": int(args.seed), "runs": len(plan),
        "scenarios": sorted(set(e["scenario"] for e in plan)),
        "faults_injected": faults_injected,
        "min_faults": int(args.min_faults),
        "failures": len(failures),
        "ok": not failures and not fault_shortfall,
        "out": outdir,
        "failed_runs": [f["run"] for f in failures],
    }
    if fault_shortfall:
        summary["fault_shortfall"] = (
            f"only {faults_injected} faults provably landed, "
            f"--min-faults wanted {args.min_faults}")
    with open(os.path.join(outdir, "chaos_summary.json"), "w") as f:
        f.write(json.dumps({"summary": summary, "results": results},
                           indent=1))
    print(json.dumps(summary), flush=True)
    return 0 if summary["ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="chaos", description="seeded chaos campaign over the "
        "self-healing training runtime")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--scenarios", default=None,
                    help="comma list (default: all "
                    f"{len(SCENARIOS)})")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--out", default=None,
                    help="campaign directory (default: a tempdir)")
    ap.add_argument("--run-timeout", type=float, default=180.0)
    ap.add_argument("--min-faults", type=int, default=0,
                    help="fail the campaign (exit 1) unless at least "
                    "this many faults PROVABLY landed — the CI gate's "
                    "enforcement of its >=N-faults claim")
    ap.add_argument("--keep", action="store_true",
                    help="keep per-run artifacts of passing runs")
    # worker half (the supervised command)
    ap.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--prefix", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--ctx", default="cpu", help=argparse.SUPPRESS)
    ap.add_argument("--pidfile", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.worker:
        return _worker(args)
    return campaign(args)


if __name__ == "__main__":
    sys.exit(main())
