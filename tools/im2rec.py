#!/usr/bin/env python
"""im2rec — build .lst/.rec datasets from an image directory.

Reference parity: tools/im2rec.py (list generation + multi-worker
packing into RecordIO with IRHeader labels).

    python tools/im2rec.py --list prefix image_dir       # make .lst
    python tools/im2rec.py prefix image_dir              # pack .rec
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=True):
    cat = {}
    items = []
    i = 0
    for path, dirs, files in sorted(os.walk(root)):
        dirs.sort()
        for f in sorted(files):
            if os.path.splitext(f)[1].lower() not in _EXTS:
                continue
            rel = os.path.relpath(os.path.join(path, f), root)
            label_dir = os.path.dirname(rel)
            if label_dir not in cat:
                cat[label_dir] = len(cat)
            items.append((i, rel, cat[label_dir]))
            i += 1
        if not recursive:
            break
    return items


def write_list(prefix, items, shuffle=False):
    if shuffle:
        random.shuffle(items)
    with open(prefix + ".lst", "w") as f:
        for idx, rel, label in items:
            f.write(f"{idx}\t{label}\t{rel}\n")


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            yield int(parts[0]), [float(x) for x in parts[1:-1]], parts[-1]


def pack(prefix, root, quality=95, resize=0):
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    count = 0
    for idx, labels, rel in read_list(prefix + ".lst"):
        with open(os.path.join(root, rel), "rb") as f:
            img = f.read()
        if resize > 0:
            from mxnet_tpu import image as img_mod

            im = img_mod.imdecode(img)
            im = img_mod.resize_short(im, resize)
            import io as _io

            from PIL import Image

            buf = _io.BytesIO()
            Image.fromarray(im.asnumpy()).save(buf, "JPEG",
                                               quality=quality)
            img = buf.getvalue()
        header = recordio.IRHeader(0, labels if len(labels) > 1
                                   else labels[0], idx, 0)
        rec.write_idx(idx, recordio.pack(header, img))
        count += 1
    rec.close()
    print(f"packed {count} records into {prefix}.rec")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst only")
    ap.add_argument("--no-shuffle", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    args = ap.parse_args()
    if args.list:
        items = list_images(args.root)
        write_list(args.prefix, items, shuffle=not args.no_shuffle)
        print(f"wrote {len(items)} entries to {args.prefix}.lst")
    else:
        if not os.path.exists(args.prefix + ".lst"):
            items = list_images(args.root)
            write_list(args.prefix, items, shuffle=not args.no_shuffle)
        pack(args.prefix, args.root, args.quality, args.resize)


if __name__ == "__main__":
    main()
