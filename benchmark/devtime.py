"""Device-side chained timing for per-op benchmarks.

Host-loop timing (launch op K times, read back, divide) is unusable on
the axon TPU tunnel: readback latency jitter of tens of ms swamps
sub-ms ops, which produced the round-3 opperf artifact where 153/370
rows had negative avg_time_ms.  This module times a K-iteration
``lax.fori_loop`` whose iterations are serialized by a genuine data
dependence (each iteration perturbs an input with a zero derived from
the previous output), executed as ONE device program with ONE scalar
readback.  The marginal per-iteration time comes from two K values, so
the constant dispatch+readback cost cancels exactly once rather than
once per iteration.

Reference analog: benchmark/opperf/utils/benchmark_utils.py times ops
under the engine profiler, which also records device time, not host
enqueue time.
"""
from __future__ import annotations

import time

import numpy as onp


def _zero_like_scalar(out, jnp):
    """A traced scalar that is always 0 but data-depends on ``out``.

    NOT ``z * 0`` — XLA's algebraic simplifier folds that to a constant,
    which severs the chain, lets the loop body dead-code-eliminate, and
    "times" an empty loop (observed: 4096^3 matmul at 4,143 TF/s, 20x
    over the chip's peak).  min(|finite(z)|, 0) is runtime-zero but not
    provably zero to the compiler."""
    outs = out if isinstance(out, (list, tuple)) else (out,)
    # the scalar must consume EVERY element of EVERY output: with a
    # partial dependence XLA slices or DCEs the producer itself
    # (observed: slice(dot) rewritten to a [1,512]x[512,1] dot, emptying
    # the loop; a tuple op's unused outputs would be eliminated the same
    # way).  The full reduces cost one extra read of the outputs per
    # iteration — documented overhead of the method.
    z = jnp.float32(0.0)
    for o in outs:
        if jnp.iscomplexobj(o):
            o = jnp.real(o)
        z = z + jnp.sum(o.astype(jnp.float32))
    z = jnp.where(jnp.isfinite(z), z, 0.0)  # NaN would poison the args
    return jnp.minimum(jnp.abs(z), 0.0)


def _perturb(args, s, jnp):
    """Inject the zero scalar into the first mutable numeric arg so the
    next iteration cannot be reordered before the previous output."""
    new = list(args)
    for i, a in enumerate(new):
        if not hasattr(a, "dtype") or a.dtype == jnp.bool_:
            continue
        if jnp.issubdtype(a.dtype, jnp.integer):
            delta = s.astype(a.dtype)
        elif a.dtype in (jnp.float32, jnp.float64, jnp.float16,
                         jnp.bfloat16) or jnp.issubdtype(
                             a.dtype, jnp.floating):
            delta = s.astype(a.dtype)
        elif jnp.issubdtype(a.dtype, jnp.complexfloating):
            delta = s.astype(a.dtype)
        else:
            continue
        if a.ndim:
            idx = (0,) * a.ndim
            new[i] = a.at[idx].add(delta)
        else:
            new[i] = a + delta
        return new
    return new  # no numeric arg: rely on jit not hoisting effectful fn


_OVERHEAD_CACHE = []


def chain_overhead():
    """Per-iteration cost of the timing skeleton itself (perturb +
    barrier + scalar reduce on a tiny array, plus the while-loop
    bookkeeping) — measured once and cached.  Sub-us ops are dominated
    by this floor, so opperf subtracts it."""
    if not _OVERHEAD_CACHE:
        import jax.numpy as jnp

        dt, _ = device_chain_time(lambda a: a, [jnp.zeros((8,))],
                                  subtract_overhead=False)
        _OVERHEAD_CACHE.append(max(dt, 0.0))
    return _OVERHEAD_CACHE[0]


def device_chain_time(fn, args, k_small=2, trials=3, target_spread=0.8,
                      max_seconds=20.0, max_runs=2_000_000,
                      subtract_overhead=False, return_samples=False):
    """Median marginal seconds per call of ``fn(*args)`` on device.

    fn must be jax-traceable with fixed shapes.  Returns (dt_seconds,
    runs_used) — or (dt_seconds, runs_used, samples) with
    ``return_samples=True``, where ``samples`` is the per-trial
    marginal-seconds list (ascending) so callers can report
    tail-latency percentiles, not just the median.  The K spread is
    sized adaptively so the marginal time (runs x dt) is
    ~``target_spread`` seconds — the tunnel's dispatch+readback
    constant jitters by tens of ms, so the spread must dwarf it —
    clamped so one timing stays under ``max_seconds``.
    """
    import jax
    import jax.numpy as jnp

    # leave pytree args (dicts/lists of arrays) alone — jit flattens
    # them; only promote bare scalars/numpy arrays
    args = [a if hasattr(a, "dtype") or isinstance(a, (dict, list, tuple))
            else jnp.asarray(a) for a in args]

    @jax.jit
    def loop(k, loop_args):
        # k is a TRACED bound (lowers to a while loop) so every K shares
        # ONE compiled program — per-op compile cost on the tunnel is
        # seconds, and three static-K programs per op tripled it
        def body(_, carry):
            cargs, s = carry
            cargs = tuple(_perturb(cargs, s, jnp))
            # barrier: keeps the perturbed args (and thus fn) from being
            # hoisted or simplified out of the loop
            cargs = jax.lax.optimization_barrier(cargs)
            out = fn(*cargs)
            return cargs, _zero_like_scalar(out, jnp)

        _, s = jax.lax.fori_loop(
            0, k, body, (tuple(loop_args), jnp.float32(0.0)))
        return s

    def run(k):
        t0 = time.perf_counter()
        s = loop(jnp.int32(k), args)
        _ = float(s)  # scalar readback drains the chain
        return time.perf_counter() - t0

    # Geometric probe ladder: grow K until the marginal time is clearly
    # above the dispatch jitter, then stop.  A single mid-size probe is
    # NOT safe: jitter can make per-iter read as ~0, and extrapolating
    # from that launched multi-minute device loops that tripped the
    # tunnel's watchdog and crashed the TPU worker (observed r04).
    run(k_small)  # compiles the program
    t_small = run(k_small)
    k = 4
    while True:
        t_k = run(k_small + k)
        delta = t_k - t_small
        if delta > target_spread / 2 or k >= max_runs \
                or t_k > max_seconds / 2:
            break
        k = min(k * 8, max_runs)
    # the ladder stops as soon as the spread is MEASURABLE (> spread/2);
    # scale up to the full target so the trials' spread dwarfs the
    # ~40 ms jitter rather than merely exceeding it, bounded by
    # max_seconds per timing
    if 0 < delta < target_spread and t_k < max_seconds / 2:
        per_iter = delta / k
        k = min(max(k, int(target_spread / per_iter)), max_runs,
                max(int((max_seconds / 2) / per_iter), k))
    runs = k
    ts = []
    for _ in range(trials):
        t1 = run(k_small)
        t2 = run(k_small + runs)
        ts.append((t2 - t1) / runs)
    ts.sort()
    dt = ts[len(ts) // 2]
    if subtract_overhead:
        oh = chain_overhead()
        dt = max(dt - oh, 0.0)
        ts = [max(t - oh, 0.0) for t in ts]
    if return_samples:
        return dt, runs, ts
    return dt, runs
