"""Device-side chained timing for per-op benchmarks.

Host-loop timing (launch op K times, read back, divide) is unusable on
the axon TPU tunnel: readback latency jitter of tens of ms swamps
sub-ms ops, which produced the round-3 opperf artifact where 153/370
rows had negative avg_time_ms.  This module times a K-iteration
``lax.fori_loop`` whose iterations are serialized by a genuine data
dependence (each iteration perturbs an input with a zero derived from
the previous output), executed as ONE device program with ONE scalar
readback.  The marginal per-iteration time comes from two K values, so
the constant dispatch+readback cost cancels exactly once rather than
once per iteration.

Reference analog: benchmark/opperf/utils/benchmark_utils.py times ops
under the engine profiler, which also records device time, not host
enqueue time.
"""
from __future__ import annotations

import time

import numpy as onp


def _zero_like_scalar(out, jnp):
    """A traced scalar that is always 0 but data-depends on ``out``.

    NOT ``z * 0`` — XLA's algebraic simplifier folds that to a constant,
    which severs the chain, lets the loop body dead-code-eliminate, and
    "times" an empty loop (observed: 4096^3 matmul at 4,143 TF/s, 20x
    over the chip's peak).  min(|finite(z)|, 0) is runtime-zero but not
    provably zero to the compiler."""
    outs = out if isinstance(out, (list, tuple)) else (out,)
    # the scalar must consume EVERY element of EVERY output: with a
    # partial dependence XLA slices or DCEs the producer itself
    # (observed: slice(dot) rewritten to a [1,512]x[512,1] dot, emptying
    # the loop; a tuple op's unused outputs would be eliminated the same
    # way).  The full reduces cost one extra read of the outputs per
    # iteration — documented overhead of the method.
    z = jnp.float32(0.0)
    for o in outs:
        if jnp.iscomplexobj(o):
            o = jnp.real(o)
        z = z + jnp.sum(o.astype(jnp.float32))
    z = jnp.where(jnp.isfinite(z), z, 0.0)  # NaN would poison the args
    return jnp.minimum(jnp.abs(z), 0.0)


def _perturb(args, s, jnp):
    """Inject the zero scalar into the first mutable numeric arg so the
    next iteration cannot be reordered before the previous output."""
    new = list(args)
    for i, a in enumerate(new):
        if not hasattr(a, "dtype") or a.dtype == jnp.bool_:
            continue
        if jnp.issubdtype(a.dtype, jnp.integer):
            delta = s.astype(a.dtype)
        elif a.dtype in (jnp.float32, jnp.float64, jnp.float16,
                         jnp.bfloat16) or jnp.issubdtype(
                             a.dtype, jnp.floating):
            delta = s.astype(a.dtype)
        elif jnp.issubdtype(a.dtype, jnp.complexfloating):
            delta = s.astype(a.dtype)
        else:
            continue
        if a.ndim:
            idx = (0,) * a.ndim
            new[i] = a.at[idx].add(delta)
        else:
            new[i] = a + delta
        return new
    return new  # no numeric arg: rely on jit not hoisting effectful fn


def device_chain_time(fn, args, k_small=2, trials=3, target_spread=0.8,
                      max_seconds=20.0, max_runs=4096):
    """Median marginal seconds per call of ``fn(*args)`` on device.

    fn must be jax-traceable with fixed shapes.  Returns (dt_seconds,
    runs_used).  The K spread is sized adaptively so the marginal time
    (runs x dt) is ~``target_spread`` seconds — the tunnel's dispatch+
    readback constant jitters by tens of ms, so the spread must dwarf
    it — clamped so one timing stays under ``max_seconds``.
    """
    import jax
    import jax.numpy as jnp
    from functools import partial

    args = [jnp.asarray(a) if not hasattr(a, "dtype") else a for a in args]

    @partial(jax.jit, static_argnums=(0,))
    def loop(k, loop_args):
        def body(_, carry):
            cargs, s = carry
            cargs = tuple(_perturb(cargs, s, jnp))
            # barrier: keeps the perturbed args (and thus fn) from being
            # hoisted or simplified out of the loop
            cargs = jax.lax.optimization_barrier(cargs)
            out = fn(*cargs)
            return cargs, _zero_like_scalar(out, jnp)

        _, s = jax.lax.fori_loop(
            0, k, body, (tuple(loop_args), jnp.float32(0.0)))
        return s

    def run(k):
        t0 = time.perf_counter()
        s = loop(k, args)
        _ = float(s)  # scalar readback drains the chain
        return time.perf_counter() - t0

    # probe with a mid-size loop to estimate per-iter cost (the small-K
    # run alone is all constant overhead for fast ops); each distinct K
    # compiles its own program, so warm both before the clock
    probe_k = 32
    run(k_small)
    run(probe_k)
    t_small = run(k_small)
    t_probe = run(probe_k)
    per_iter = max((t_probe - t_small) / (probe_k - k_small), 1e-7)
    runs = max(8, min(int(target_spread / per_iter), max_runs,
                      max(int(max_seconds / per_iter), 8)))
    if runs == probe_k - k_small:
        runs += 1  # reuse-distinct program size (separate jit cache key)
    run(k_small + runs)  # compile the big-K program before the clock
    ts = []
    for _ in range(trials):
        t1 = run(k_small)
        t2 = run(k_small + runs)
        ts.append((t2 - t1) / runs)
    ts.sort()
    return ts[len(ts) // 2], runs
