#!/usr/bin/env python
"""Per-operator benchmark harness (reference: benchmark/opperf/ —
opperf.py runs every registered op with timing via the profiler).

Times eager dispatch+execution of registered ops on representative
shapes, emitting one JSON line per op:

    python benchmark/opperf.py [--ops dot,Convolution] [--runs 25]
        [--large]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.ops.registry import get_op, list_ops  # noqa: E402


def _standard_inputs(large=False):
    n = 1024 if large else 128
    a = onp.random.rand(n, n).astype("float32")
    return {
        # (inputs, params) per op family; unary/binary auto-probe below
        "dot": ([a, a], {}),
        "batch_dot": ([onp.random.rand(8, n, 64).astype("float32"),
                       onp.random.rand(8, 64, n).astype("float32")], {}),
        "FullyConnected": ([a, a, onp.zeros(n, "float32")],
                           dict(num_hidden=n)),
        "Convolution": ([onp.random.rand(8, 32, 64, 64).astype("float32"),
                         onp.random.rand(64, 32, 3, 3).astype("float32"),
                         onp.zeros(64, "float32")],
                        dict(kernel=(3, 3), num_filter=64, pad=(1, 1))),
        "Pooling": ([onp.random.rand(8, 32, 64, 64).astype("float32")],
                    dict(kernel=(2, 2), stride=(2, 2))),
        "BatchNorm": ([onp.random.rand(8, 32, 32, 32).astype("float32"),
                       onp.ones(32, "float32"), onp.zeros(32, "float32"),
                       onp.zeros(32, "float32"), onp.ones(32, "float32")],
                      {}),
        # fused bn->relu->1x1conv (ops/pallas_conv.py): NHWC input,
        # channel-last O11I weight
        "_contrib_BNReluConv": (
            [onp.random.rand(4, 8, 8, 16).astype("float32") + 0.1,
             onp.random.rand(16).astype("float32") + 0.5,
             onp.random.rand(16).astype("float32") * 0.2,
             onp.random.rand(24, 1, 1, 16).astype("float32") * 0.3],
            {}),
        "softmax": ([a], {}),
        "sum": ([a], {}),
        "transpose": ([a], {}),
        "sort": ([a], {}),
        "_npi_einsum": ([a, a], dict(subscripts="ij,jk->ik")),
        **_family_inputs(),
    }


def _family_inputs():
    """Specs for ops whose required hyper-params defeat the auto-probe
    (the reference opperf's per-op rule tables)."""
    img = onp.random.rand(8, 16, 32, 32).astype("float32")
    vec16 = onp.ones(16, "float32")
    z16 = onp.zeros(16, "float32")
    seq = onp.random.rand(16, 8, 32).astype("float32")
    rois = onp.array([[0, 2, 2, 20, 20], [4, 1, 1, 16, 16]], "float32")
    anchors = onp.random.rand(1, 64, 4).astype("float32")
    cls_prob = onp.random.rand(2, 3, 64).astype("float32")
    loc_pred = onp.random.rand(2, 256).astype("float32")
    det_label = onp.array([[[0, .1, .1, .4, .4]], [[1, .5, .5, .9, .9]]],
                          "float32")
    from mxnet_tpu.ops.rnn import rnn_param_size

    psz = rnn_param_size("lstm", 1, 32, 64)
    qkv = onp.random.rand(16, 4, 96).astype("float32")
    return {
        "Activation": ([img], dict(act_type="relu")),
        "LeakyReLU": ([img], dict(act_type="leaky")),
        "Cast": ([img], dict(dtype="float16")),
        "Pad": ([img], dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
        "UpSampling": ([img], dict(scale=2, sample_type="nearest")),
        "SliceChannel": ([img], dict(num_outputs=2)),
        "LayerNorm": ([img, vec16, z16], dict(axis=1)),
        "GroupNorm": ([img, onp.ones(4, "float32"),
                       onp.zeros(4, "float32")], dict(num_groups=4)),
        "InstanceNorm": ([img, vec16, z16], {}),
        "SyncBatchNorm": ([img, vec16, z16, z16.copy(), vec16.copy()],
                          {}),
        "Deconvolution": ([img, onp.random.rand(16, 8, 3, 3)
                           .astype("float32")],
                          dict(kernel=(3, 3), num_filter=8,
                               stride=(2, 2), pad=(1, 1))),
        "DeformableConvolution": (
            [img, onp.zeros((8, 18, 32, 32), "float32"),
             onp.random.rand(16, 16, 3, 3).astype("float32")],
            dict(kernel=(3, 3), num_filter=16, pad=(1, 1),
                 no_bias=True)),
        "BilinearResize2D": ([img], dict(height=64, width=64)),
        "AdaptiveAvgPooling2D": ([img], dict(output_size=(4, 4))),
        "Correlation": ([img, img.copy()],
                        dict(kernel_size=1, max_displacement=2,
                             pad_size=2)),
        "GridGenerator": ([onp.random.rand(8, 6).astype("float32")],
                          dict(transform_type="affine",
                               target_shape=(16, 16))),
        "ROIPooling": ([img, rois],
                       dict(pooled_size=(4, 4), spatial_scale=1.0)),
        "_contrib_ROIAlign": ([img, rois],
                              dict(pooled_size=(4, 4),
                                   spatial_scale=1.0)),
        "RNN": ([seq, onp.random.uniform(-0.1, 0.1, psz)
                 .astype("float32"),
                 onp.zeros((1, 8, 64), "float32"),
                 onp.zeros((1, 8, 64), "float32")],
                dict(state_size=64, num_layers=1, mode="lstm")),
        "_contrib_MultiBoxPrior": ([img], dict(sizes=(0.5,),
                                               ratios=(1.0,))),
        "_contrib_MultiBoxDetection": ([cls_prob, loc_pred, anchors],
                                       {}),
        "_contrib_MultiBoxTarget": ([anchors, det_label,
                                     cls_prob], {}),
        "_contrib_box_iou": ([onp.random.rand(8, 4).astype("float32"),
                              onp.random.rand(8, 4).astype("float32")],
                             {}),
        "_contrib_interleaved_matmul_selfatt_qk": ([qkv],
                                                   dict(heads=8)),
        "_contrib_interleaved_matmul_selfatt_valatt": (
            [qkv, onp.random.rand(32, 16, 16).astype("float32")],
            dict(heads=8)),
        "_contrib_quantize_v2": ([img], {}),
        "_contrib_dequantize": (
            [onp.random.randint(-127, 127, (16, 16)).astype("int8"),
             onp.array([-1.0], "float32"), onp.array([1.0], "float32")],
            {}),
        "one_hot": ([onp.arange(16, dtype="float32")], dict(depth=32)),
        "Embedding": ([onp.arange(16, dtype="float32"),
                       onp.random.rand(100, 32).astype("float32")],
                      dict(input_dim=100, output_dim=32)),
        "SequenceMask": ([seq], {}),
        "topk": ([onp.random.rand(16, 64).astype("float32")],
                 dict(k=4)),
        "pick": ([onp.random.rand(16, 8).astype("float32"),
                  onp.zeros(16, "float32")], {}),
        # ---- kwarg-required tail (r04: the grad sweep and opperf share
        # this table; every differentiable op needs a probeable spec)
        "_plus_scalar": ([img], dict(scalar=2.0)),
        "_minus_scalar": ([img], dict(scalar=2.0)),
        "_rminus_scalar": ([img], dict(scalar=2.0)),
        "_mul_scalar": ([img], dict(scalar=2.0)),
        "_div_scalar": ([img], dict(scalar=2.0)),
        "_power_scalar": ([img], dict(scalar=2.0)),
        # (_mod/_rmod/_rdiv/_rpower scalar variants live in the
        # FD-conditioned block below)
        "_maximum_scalar": ([img], dict(scalar=0.5)),
        "_minimum_scalar": ([img], dict(scalar=0.5)),
        "clip": ([img], dict(a_min=0.2, a_max=0.8)),
        "tile": ([onp.random.rand(8, 8).astype("float32")],
                 dict(reps=(2, 3))),
        "repeat": ([onp.random.rand(8, 8).astype("float32")],
                   dict(repeats=3)),
        "flip": ([onp.random.rand(8, 8).astype("float32")],
                 dict(axis=0)),
        "expand_dims": ([onp.random.rand(8, 8).astype("float32")],
                        dict(axis=1)),
        "slice": ([onp.random.rand(16, 16).astype("float32")],
                  dict(begin=(2, 2), end=(10, 12))),
        "slice_axis": ([onp.random.rand(16, 16).astype("float32")],
                       dict(axis=0, begin=2, end=10)),
        "broadcast_to": ([onp.random.rand(1, 16).astype("float32")],
                         dict(shape=(8, 16))),
        "broadcast_axes": ([onp.random.rand(1, 16).astype("float32")],
                           dict(axis=0, size=8)),
        "depth_to_space": ([onp.random.rand(2, 8, 4, 4)
                            .astype("float32")], dict(block_size=2)),
        "space_to_depth": ([onp.random.rand(2, 2, 8, 8)
                            .astype("float32")], dict(block_size=2)),
        "split_v2": ([onp.random.rand(8, 16).astype("float32")],
                     dict(indices=(2, 5), _num=3)),
        "gather_nd": ([onp.random.rand(8, 8).astype("float32"),
                       onp.array([[0, 2, 4], [1, 3, 5]], "int64")], {}),
        "scatter_nd": ([onp.random.rand(3).astype("float32"),
                        onp.array([[0, 2, 4]], "int64")],
                       dict(shape=(8,))),
        "batch_take": ([onp.random.rand(16, 16).astype("float32"),
                        onp.arange(16, dtype="int64")], {}),
        "take": ([onp.random.rand(32, 8).astype("float32"),
                  onp.arange(16, dtype="int64")], {}),
        "amp_cast": ([img], dict(dtype="float32")),
        "amp_multicast": ([img, img.copy()], dict(num_outputs=2)),
        "_contrib_dot_product_attention": (
            [onp.random.rand(2, 16, 32).astype("float32"),
             onp.random.rand(2, 16, 32).astype("float32"),
             onp.random.rand(2, 16, 32).astype("float32")],
            dict(num_heads=4, interpret=True)),
        "_random_pdf_uniform": (
            [onp.random.uniform(0.4, 0.6, (8, 16)).astype("float32"),
             onp.full((8,), 0.05, "float32"),
             onp.full((8,), 0.95, "float32")], {}),
        "_random_pdf_dirichlet": (
            [_simplex(8, 4), onp.random.uniform(1.5, 2.5, (8, 4))
             .astype("float32")], {}),
        # conditioned linalg inputs: random 128x128 determinants/
        # inverses are numerically meaningless for FD checks
        "_linalg_det": ([_spd(6)], {}),
        "_npi_det": ([_spd(6)], {}),
        "_linalg_potrf": ([_spd(6)], {}),
        "_npi_cholesky": ([_spd(6)], {}),
        "_linalg_potri": ([_spd(6)], {}),
        "_linalg_trsm": ([_tril(6), onp.random.rand(6, 6)
                          .astype("float32")], {}),
        "_npi_tensorinv": ([_spd(6).reshape(2, 3, 2, 3)], dict(ind=2)),
        "_npi_matrix_power": ([_spd(6)], dict(n=2)),
        "_npi_cross": ([onp.random.rand(8, 3).astype("float32"),
                        onp.random.rand(8, 3).astype("float32")], {}),
        "_npi_moveaxis": ([onp.random.rand(4, 6, 8).astype("float32")],
                          dict(source=0, destination=2)),
        "_npi_roll": ([onp.random.rand(8, 8).astype("float32")],
                      dict(shift=3, axis=1)),
        "_npi_rollaxis": ([onp.random.rand(4, 6, 8).astype("float32")],
                          dict(axis=2, start=0)),
        "_npi_take_along_axis": (
            [onp.random.rand(8, 8).astype("float32"),
             onp.random.randint(0, 8, (8, 4)).astype("int64")],
            dict(axis=1)),
        "_np_arccosh": ([onp.random.uniform(1.5, 3.0, (8, 16))
                         .astype("float32")], {}),
        "_hypot_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                           .astype("float32")], dict(scalar=2.0)),
        # denominators bounded away from numerator range: keeps the
        # fmod/floor family off its kink lattice for FD
        "_mod": ([onp.random.uniform(0.1, 0.4, (8, 16))
                  .astype("float32"),
                  onp.random.uniform(0.6, 0.9, (8, 16))
                  .astype("float32")], {}),
        "_npi_fmod": ([onp.random.uniform(0.1, 0.4, (8, 16))
                       .astype("float32"),
                       onp.random.uniform(0.6, 0.9, (8, 16))
                       .astype("float32")], {}),
        "_npi_floor_divide": ([onp.random.uniform(0.1, 0.4, (8, 16))
                               .astype("float32"),
                               onp.random.uniform(0.6, 0.9, (8, 16))
                               .astype("float32")], {}),
        "_mod_scalar": ([onp.random.uniform(0.1, 0.9, (8, 16))
                         .astype("float32")], dict(scalar=2.0)),
        "_rmod_scalar": ([onp.random.uniform(1.1, 1.9, (8, 16))
                          .astype("float32")], dict(scalar=1.0)),
        "_rdiv_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                          .astype("float32")], dict(scalar=2.0)),
        "_rpower_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                            .astype("float32")], dict(scalar=2.0)),
        "CTCLoss": ([onp.random.rand(10, 2, 6).astype("float32"),
                     onp.array([[1, 2, 3, 0], [2, 4, 0, 0]],
                               "float32")], {}),
        "BilinearSampler": (
            [onp.random.rand(2, 3, 8, 8).astype("float32"),
             onp.random.uniform(-0.9, 0.9, (2, 2, 8, 8))
             .astype("float32")], {}),
        "SpatialTransformer": (
            [onp.random.rand(2, 3, 8, 8).astype("float32"),
             onp.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]] * 2,
                       "float32")],
            dict(target_shape=(8, 8), transform_type="affine",
                 sampler_type="bilinear")),
        "_contrib_interleaved_matmul_encdec_qk": (
            [onp.random.rand(12, 2, 32).astype("float32"),
             onp.random.rand(10, 2, 64).astype("float32")],
            dict(heads=4)),
        "_contrib_interleaved_matmul_encdec_valatt": (
            [onp.random.rand(10, 2, 64).astype("float32"),
             onp.random.rand(8, 12, 10).astype("float32")],
            dict(heads=4)),
    }


def _spd(n):
    a = onp.random.RandomState(3).rand(n, n).astype("float32")
    m = a @ a.T + n * onp.eye(n, dtype="float32")
    # normalize so det ~ O(1): determinant-family FD otherwise sweeps
    # the loss's cos() through multiple periods per epsilon step
    return (m / n).astype("float32")


def _tril(n):
    a = onp.tril(onp.random.RandomState(4).rand(n, n)).astype("float32")
    return a + n * onp.eye(n, dtype="float32")


def _simplex(b, k):
    a = onp.random.RandomState(5).rand(b, k).astype("float32") + 0.2
    return a / a.sum(-1, keepdims=True)


def bench_op(opname, inputs, params, ctx, runs):
    """Marginal per-call device time via the chained fori_loop timer
    (benchmark/devtime.py).  Round 3's host-loop two-K sweep produced
    153 negative timings out of 370 rows — tunnel readback jitter
    swamped sub-ms ops; the device-side chain makes that impossible by
    construction (one program, one scalar readback, data-dependent
    iterations)."""
    import jax

    from devtime import device_chain_time

    op = get_op(opname)
    vals = [mx.nd.array(x, ctx=ctx)._data for x in inputs]
    kwargs = dict(params)

    if not vals and op.key_param:
        # zero-input sampler: the chained timer needs a data
        # dependence or XLA hoists the draw out of the loop (measuring
        # an empty body).  Fold the chain's perturbed dummy counter
        # into the PRNG key so every iteration draws fresh.
        base_key = jax.random.key(0)
        dummy = mx.nd.array(onp.zeros((1,), "int32"), ctx=ctx)._data

        def fn(d):
            kw = dict(kwargs)
            kw[op.key_param] = jax.random.fold_in(base_key, d[0])
            return op.fn(**kw)

        vals = [dummy]
    else:
        if op.key_param and op.key_param not in kwargs:
            kwargs[op.key_param] = jax.random.key(0)

        def fn(*args):
            return op.fn(*args, **kwargs)

    dt, _, samples = device_chain_time(
        fn, vals, target_spread=0.4,
        trials=max(3, min(runs // 8, 5)),
        subtract_overhead=True, return_samples=True)
    return dt, samples


# ops whose signatures genuinely need bespoke shapes/params beyond the
# curated table and the auto-probe (IO-coupled, subgraph-attr, or
# index-typed inputs); everything else in the registry gets timed
SKIP_OPS = frozenset((
    "_foreach", "_while_loop", "_cond",  # subgraph-JSON attrs
    "custom",  # user-provided op body
    # complex-valued iFFT is UNIMPLEMENTED on the axon TPU backend, and
    # a failed execution poisons the tunnel stream for every op after
    # it — keep it out of the sweep
    "_contrib_ifft",
))

#: ops the chained timer CANNOT measure honestly, each with the reason
#: (the grad sweep's SKIP_JUSTIFICATIONS discipline applied here —
#: every registered non-alias op is timed or justified)
JUSTIFIED_SKIPS = {
    "_npi_hanning": "zero-input deterministic generator: loop-"
                    "invariant, XLA hoists it out of the chained loop "
                    "so only a per-iteration copy would be timed",
    "_npi_hamming": "zero-input deterministic generator (see hanning)",
    "_npi_blackman": "zero-input deterministic generator (see hanning)",
    "_npi_bartlett": "zero-input deterministic generator (see hanning)",
    "_npi_indices": "zero-input deterministic generator (see hanning)",
    "_npi_tri": "zero-input deterministic generator (see hanning)",
    "_contrib_count_sketch": "integer hash-index inputs: the chain's "
                             "float perturbation corrupts them",
    "_getitem": "python-object `key` parameter (slices/ellipsis): not "
                "a tensor program knob; covered by crop/slice timings",
}


def _bench_extra_inputs():
    """Curated specs for ops the auto-probe cannot type out: optimizer
    update rules, scalar-compare family, quantized conv/fc, MultiBox*,
    numpy tail ops, random samplers (reference
    benchmark/opperf/utils/op_registry_utils.py keeps the same
    per-family registries)."""
    n = 1024
    a = onp.random.rand(n, n).astype("float32")
    v = onp.random.rand(n).astype("float32")
    ints = onp.random.randint(0, 255, (n, n)).astype("int32")
    q8 = onp.random.randint(-127, 127, (8, 32, 32, 32)).astype("int8")
    w8 = onp.random.randint(-127, 127, (64, 32, 3, 3)).astype("int8")
    mm = onp.float32
    opt = {
        "sgd_update": ([a, a], dict(lr=0.1)),
        "sgd_mom_update": ([a, a, a], dict(lr=0.1, momentum=0.9)),
        "nag_mom_update": ([a, a, a], dict(lr=0.1, momentum=0.9)),
        "adam_update": ([a, a, a, a], dict(lr=0.1)),
        "rmsprop_update": ([a, a, a], dict(lr=0.1)),
        "rmspropalex_update": ([a, a, a, a, a], dict(lr=0.1)),
        "ftrl_update": ([a, a, a, a], dict(lr=0.1)),
        "signsgd_update": ([a, a], dict(lr=0.1)),
        "signum_update": ([a, a, a], dict(lr=0.1, momentum=0.9)),
        "multi_sgd_update": ([a, a], dict(lrs=(0.1,), wds=(0.0,),
                                          num_weights=1)),
        "multi_sgd_mom_update": ([a, a, a],
                                 dict(lrs=(0.1,), wds=(0.0,),
                                      num_weights=1)),
        "multi_lars": ([v, v, v, v], dict(eta=0.001, eps=1e-8)),
        # _sparse_adagrad_update is an alias of adagrad_update (timed)
    }
    # bucketed flat-tensor rows (round 9): one launch over a 1M-element
    # flat bucket — the sharded-server exchange's inner update as
    # benchmarked ops (the multi_mp_sgd/multi_lars analog); seg_ids
    # partitions the bucket into 16 "parameters" for the LARS trust
    # ratios (int input: the chain perturbation adds an integer 0)
    flat = onp.random.rand(n * n).astype("float32")
    seg = onp.repeat(onp.arange(16, dtype="int32"), (n * n) // 16)
    opt.update({
        "_fused_bucket_sgd_mom_update": (
            [flat, flat.copy(), flat.copy()],
            dict(lr=0.1, momentum=0.9)),
        "_fused_bucket_adam_update": (
            [flat, flat.copy(), flat.copy(), flat.copy()],
            dict(lr=0.1)),
        "_fused_bucket_lars_update": (
            [flat, flat.copy(), flat.copy(), seg],
            dict(lr=0.1, momentum=0.9, num_segments=16)),
        # round 14: the Pallas fused-bucket kernel arms of the same
        # three updates (ops/pallas_opt.py — prep + rule + loss-scale
        # check in one VMEM pass; interpret mode off-TPU) so benchdiff
        # trends kernel-vs-jnp per round
        "_pallas_bucket_sgd_mom_update": (
            [flat, flat.copy(), flat.copy()],
            dict(lr=0.1, momentum=0.9)),
        "_pallas_bucket_adam_update": (
            [flat, flat.copy(), flat.copy(), flat.copy()],
            dict(lr=0.1)),
        "_pallas_bucket_lars_update": (
            [flat, flat.copy(), flat.copy(), seg],
            dict(lr=0.1, momentum=0.9, num_segments=16)),
        # round 16: the bucket WIRE beside the bucket update — the
        # stage-2/3 backward reduce-scatter and stage-3 forward
        # all-gather (ops/collective_ops.py) at the same 1M-element
        # flat-bucket shape, so one jsonl round shows exchange and
        # update cost on the same x-axis; on the 1-device smoke both
        # degenerate to the copy floor (zero-communication baseline)
        "reduce_scatter": ([flat], {}),
        "all_gather": ([flat], {}),
    })
    scalar_cmp = {
        name: ([a], dict(scalar=0.5))
        for name in ("_equal_scalar", "_not_equal_scalar",
                     "_greater_scalar", "_greater_equal_scalar",
                     "_lesser_scalar", "_lesser_equal_scalar")
    }
    rand = {
        # zero-input samplers: bench_op folds the chain's perturbed
        # dummy into the PRNG key, so every iteration draws fresh
        name: ([], dict(shape=(n, n)))
        for name in ("_random_uniform", "_random_normal",
                     "_random_exponential", "_random_poisson",
                     "_random_gamma", "_random_negative_binomial",
                     "_random_generalized_negative_binomial")
    }
    rand["_random_randint"] = ([], dict(low=0, high=100, shape=(n, n)))
    npi = {
        "_npi_bincount": ([onp.random.randint(0, 512, n * 16)
                           .astype(mm)], {}),
        "_npi_bitwise_and": ([ints, ints], {}),
        "_npi_bitwise_or": ([ints, ints], {}),
        "_npi_bitwise_xor": ([ints, ints], {}),
        "_npi_bitwise_not": ([ints], {}),
        "_npi_left_shift": ([ints, onp.full((n, n), 2, "int32")], {}),
        "_npi_right_shift": ([ints, onp.full((n, n), 2, "int32")], {}),
        "_npi_full_like": ([a], dict(fill_value=3.0)),
        "_npi_delete": ([v], dict(obj=5, axis=0)),
        "_npi_insert": ([v, onp.float32([1.5])], dict(obj=5, axis=0)),
        "_npi_interp": ([onp.sort(v), onp.sort(v),
                         onp.random.rand(n).astype(mm)], {}),
        "_npi_percentile": ([a], dict(q=50.0)),
        "_npi_quantile": ([a], dict(q=0.5)),
        "_npi_resize": ([a], dict(new_shape=(n // 2, 2 * n))),
        # bucketized static-size variants (the jit contract for
        # value-dependent output shapes)
        "_npi_unique": ([onp.random.randint(0, 256, (n * 64,))
                         .astype(mm)], dict(size=256)),
        "_npi_nonzero": ([(onp.random.rand(n, n) > 0.5)
                          .astype(mm)], dict(size=n * n)),
        "crop": ([a], dict(begin=(8, 8), end=(n - 8, n - 8))),
    }
    quant = {
        "_contrib_quantize": ([a, onp.float32([0.0]),
                               onp.float32([1.0])], {}),
        # round 18: the calibrated-range entry point the quantized
        # rewrite stitches in front of every int8 layer — timed beside
        # dot/Convolution/FullyConnected so the int8-vs-fp32 per-op
        # ratio is visible in the benchdiff table
        "_contrib_quantize_v2": (
            [a], dict(min_calib_range=-1.0, max_calib_range=1.0)),
        "_contrib_requantize": (
            [onp.random.randint(-2**20, 2**20, (n, n)).astype("int32"),
             onp.float32([-1.0]), onp.float32([1.0])], {}),
        "_contrib_quantized_conv": (
            [q8, w8, onp.zeros(64, "int8"),
             onp.float32([-1]), onp.float32([1]), onp.float32([-1]),
             onp.float32([1]), onp.float32([-1]), onp.float32([1])],
            dict(kernel=(3, 3), num_filter=64, pad=(1, 1))),
        "_contrib_quantized_fully_connected": (
            [onp.random.randint(-127, 127, (128, 256)).astype("int8"),
             onp.random.randint(-127, 127, (512, 256)).astype("int8"),
             onp.zeros(512, "int8"),
             onp.float32([-1]), onp.float32([1]), onp.float32([-1]),
             onp.float32([1]), onp.float32([-1]), onp.float32([1])],
            dict(num_hidden=512)),
    }
    nb = 256  # boxes per image for the detection family
    anchors = onp.random.rand(1, nb, 4).astype(mm)
    det = {
        "MultiBoxPrior": ([onp.random.rand(8, 3, 64, 64).astype(mm)],
                          dict(sizes=(0.5, 0.25), ratios=(1.0, 2.0))),
        "MultiBoxTarget": ([anchors,
                            onp.random.rand(8, 4, 5).astype(mm),
                            onp.random.rand(8, 4, nb).astype(mm)], {}),
        "MultiBoxDetection": ([
            onp.random.rand(8, 4, nb).astype(mm),
            onp.random.rand(8, nb * 4).astype(mm), anchors], {}),
        "_contrib_Proposal": ([
            onp.random.rand(2, 2 * 9, 16, 16).astype(mm),
            onp.random.rand(2, 4 * 9, 16, 16).astype(mm),
            onp.tile(onp.float32([256, 256, 1.0]), (2, 1))],
            dict(scales=(2, 4, 8), ratios=(0.5, 1, 2),
                 rpn_pre_nms_top_n=512, rpn_post_nms_top_n=128,
                 rpn_min_size=1)),
        "_contrib_hawkesll": ([
            onp.random.rand(4).astype(mm) + 0.5,
            onp.random.rand(4).astype(mm) * 0.5,
            onp.random.rand(4).astype(mm) + 0.5,
            onp.zeros((8, 4), mm),
            onp.random.rand(8, 100).astype(mm),
            onp.random.randint(0, 4, (8, 100)).astype(mm),
            onp.full((8,), 100.0, mm),
            onp.full((8,), 120.0, mm)], {}),
    }
    return {**opt, **scalar_cmp, **rand, **npi, **quant, **det}


def auto_inputs(opname):
    """Probe an input signature: square activations at several arities,
    with a per-family shape heuristic for common tensor+vector ops."""
    op = get_op(opname)
    x = onp.random.uniform(0.3, 0.9, (128, 128)).astype("float32")
    v = onp.random.uniform(0.3, 0.9, (128,)).astype("float32")
    candidates = [[x], [x, x], [x, x, x], [v], [v, v], [x, v]]
    for args in candidates:
        try:
            vals = [mx.nd.array(a)._data for a in args]
            kwargs = {}
            if op.key_param:
                import jax

                kwargs[op.key_param] = jax.random.key(0)
            out = op.fn(*vals, **kwargs)
            if isinstance(out, (tuple, list)) and len(out) == 0:
                return None
            return args, {}
        except Exception:
            continue
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma list; default = curated + all probe-able")
    ap.add_argument("--runs", type=int, default=25)
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--baseline", default=None,
                    help="prior OPPERF jsonl; adds per-op regression "
                         "columns (prev_ms, speedup)")
    args = ap.parse_args()

    prev = {}
    if args.baseline:
        with open(args.baseline) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "op" in row and "avg_time_ms" in row:
                    prev[row["op"]] = row["avg_time_ms"]

    ctx = mx.gpu(0)
    curated = {**_standard_inputs(args.large), **_bench_extra_inputs()}
    if args.ops:
        names = args.ops.split(",")
    else:
        # registry-wide (reference opperf runs every registered op):
        # curated shapes win, auto-probe covers the rest, SKIP_OPS
        # documents the ops needing bespoke harnesses
        seen_defs = {}
        for o in sorted(list_ops()):
            if o in SKIP_OPS:
                continue
            seen_defs.setdefault(id(get_op(o)), o)  # dedupe aliases
        names = sorted(set(list(curated) + list(seen_defs.values())))
    skipped = []
    justified = {}
    for name in names:
        if name in JUSTIFIED_SKIPS:
            justified[name] = JUSTIFIED_SKIPS[name]
            continue
        if name in curated:
            spec = curated[name]
        else:
            spec = auto_inputs(name)
            if spec is None:
                skipped.append(name)
                continue
        try:
            dt, samples = bench_op(name, spec[0], spec[1], ctx,
                                   args.runs)
        except Exception as e:
            # a curated or explicitly requested op failing must be
            # visible; only blind auto-probe misses go to the skip list
            if args.ops or name in curated:
                print(json.dumps({"op": name, "error": repr(e)}),
                      flush=True)
            if not args.ops:
                skipped.append(name)
            continue
        # avg is now a TRUE mean over the per-trial marginal times
        # (it used to alias device_chain_time's median, which made the
        # p50 column a duplicate); p50/p99 are nearest-rank (the
        # shared telemetry.opstats convention), so tools/benchdiff.py
        # trends tail latency alongside the mean
        from mxnet_tpu.telemetry.opstats import percentile

        samples = sorted(samples) or [dt]
        mean = sum(samples) / len(samples)
        p50 = percentile(samples, 0.50)
        p99 = percentile(samples, 0.99)
        row = {"op": name, "avg_time_ms": round(mean * 1e3, 4),
               "p50_time_ms": round(p50 * 1e3, 4),
               "p99_time_ms": round(p99 * 1e3, 4),
               "trials": len(samples),
               "method": "device-chain"}
        if name in prev:
            row["prev_ms"] = prev[name]
            if prev[name] > 0 and dt > 0:
                row["speedup_vs_prev"] = round(prev[name] / (dt * 1e3), 2)
        print(json.dumps(row), flush=True)
    # coverage gate (the grad sweep's discipline): every registered
    # non-alias op is timed, justified, or listed as a visible failure
    print(json.dumps({"skipped_unprobeable": len(skipped),
                      "ops": sorted(skipped),
                      "justified_skips": justified}), flush=True)


if __name__ == "__main__":
    main()
