#!/usr/bin/env python
"""Per-operator benchmark harness (reference: benchmark/opperf/ —
opperf.py runs every registered op with timing via the profiler).

Times eager dispatch+execution of registered ops on representative
shapes, emitting one JSON line per op:

    python benchmark/opperf.py [--ops dot,Convolution] [--runs 25]
        [--large]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.ops.registry import get_op, list_ops  # noqa: E402


def _standard_inputs(large=False):
    n = 1024 if large else 128
    a = onp.random.rand(n, n).astype("float32")
    return {
        # (inputs, params) per op family; unary/binary auto-probe below
        "dot": ([a, a], {}),
        "batch_dot": ([onp.random.rand(8, n, 64).astype("float32"),
                       onp.random.rand(8, 64, n).astype("float32")], {}),
        "FullyConnected": ([a, a, onp.zeros(n, "float32")],
                           dict(num_hidden=n)),
        "Convolution": ([onp.random.rand(8, 32, 64, 64).astype("float32"),
                         onp.random.rand(64, 32, 3, 3).astype("float32"),
                         onp.zeros(64, "float32")],
                        dict(kernel=(3, 3), num_filter=64, pad=(1, 1))),
        "Pooling": ([onp.random.rand(8, 32, 64, 64).astype("float32")],
                    dict(kernel=(2, 2), stride=(2, 2))),
        "BatchNorm": ([onp.random.rand(8, 32, 32, 32).astype("float32"),
                       onp.ones(32, "float32"), onp.zeros(32, "float32"),
                       onp.zeros(32, "float32"), onp.ones(32, "float32")],
                      {}),
        # fused bn->relu->1x1conv (ops/pallas_conv.py): NHWC input,
        # channel-last O11I weight
        "_contrib_BNReluConv": (
            [onp.random.rand(4, 8, 8, 16).astype("float32") + 0.1,
             onp.random.rand(16).astype("float32") + 0.5,
             onp.random.rand(16).astype("float32") * 0.2,
             onp.random.rand(24, 1, 1, 16).astype("float32") * 0.3],
            {}),
        "softmax": ([a], {}),
        "sum": ([a], {}),
        "transpose": ([a], {}),
        "sort": ([a], {}),
        "_npi_einsum": ([a, a], dict(subscripts="ij,jk->ik")),
        **_family_inputs(),
    }


def _family_inputs():
    """Specs for ops whose required hyper-params defeat the auto-probe
    (the reference opperf's per-op rule tables)."""
    img = onp.random.rand(8, 16, 32, 32).astype("float32")
    vec16 = onp.ones(16, "float32")
    z16 = onp.zeros(16, "float32")
    seq = onp.random.rand(16, 8, 32).astype("float32")
    rois = onp.array([[0, 2, 2, 20, 20], [4, 1, 1, 16, 16]], "float32")
    anchors = onp.random.rand(1, 64, 4).astype("float32")
    cls_prob = onp.random.rand(2, 3, 64).astype("float32")
    loc_pred = onp.random.rand(2, 256).astype("float32")
    det_label = onp.array([[[0, .1, .1, .4, .4]], [[1, .5, .5, .9, .9]]],
                          "float32")
    from mxnet_tpu.ops.rnn import rnn_param_size

    psz = rnn_param_size("lstm", 1, 32, 64)
    qkv = onp.random.rand(16, 4, 96).astype("float32")
    return {
        "Activation": ([img], dict(act_type="relu")),
        "LeakyReLU": ([img], dict(act_type="leaky")),
        "Cast": ([img], dict(dtype="float16")),
        "Pad": ([img], dict(mode="constant",
                            pad_width=(0, 0, 0, 0, 1, 1, 1, 1))),
        "UpSampling": ([img], dict(scale=2, sample_type="nearest")),
        "SliceChannel": ([img], dict(num_outputs=2)),
        "LayerNorm": ([img, vec16, z16], dict(axis=1)),
        "GroupNorm": ([img, onp.ones(4, "float32"),
                       onp.zeros(4, "float32")], dict(num_groups=4)),
        "InstanceNorm": ([img, vec16, z16], {}),
        "SyncBatchNorm": ([img, vec16, z16, z16.copy(), vec16.copy()],
                          {}),
        "Deconvolution": ([img, onp.random.rand(16, 8, 3, 3)
                           .astype("float32")],
                          dict(kernel=(3, 3), num_filter=8,
                               stride=(2, 2), pad=(1, 1))),
        "DeformableConvolution": (
            [img, onp.zeros((8, 18, 32, 32), "float32"),
             onp.random.rand(16, 16, 3, 3).astype("float32")],
            dict(kernel=(3, 3), num_filter=16, pad=(1, 1),
                 no_bias=True)),
        "BilinearResize2D": ([img], dict(height=64, width=64)),
        "AdaptiveAvgPooling2D": ([img], dict(output_size=(4, 4))),
        "Correlation": ([img, img.copy()],
                        dict(kernel_size=1, max_displacement=2,
                             pad_size=2)),
        "GridGenerator": ([onp.random.rand(8, 6).astype("float32")],
                          dict(transform_type="affine",
                               target_shape=(16, 16))),
        "ROIPooling": ([img, rois],
                       dict(pooled_size=(4, 4), spatial_scale=1.0)),
        "_contrib_ROIAlign": ([img, rois],
                              dict(pooled_size=(4, 4),
                                   spatial_scale=1.0)),
        "RNN": ([seq, onp.random.uniform(-0.1, 0.1, psz)
                 .astype("float32"),
                 onp.zeros((1, 8, 64), "float32"),
                 onp.zeros((1, 8, 64), "float32")],
                dict(state_size=64, num_layers=1, mode="lstm")),
        "_contrib_MultiBoxPrior": ([img], dict(sizes=(0.5,),
                                               ratios=(1.0,))),
        "_contrib_MultiBoxDetection": ([cls_prob, loc_pred, anchors],
                                       {}),
        "_contrib_MultiBoxTarget": ([anchors, det_label,
                                     cls_prob], {}),
        "_contrib_box_iou": ([onp.random.rand(8, 4).astype("float32"),
                              onp.random.rand(8, 4).astype("float32")],
                             {}),
        "_contrib_interleaved_matmul_selfatt_qk": ([qkv],
                                                   dict(heads=8)),
        "_contrib_interleaved_matmul_selfatt_valatt": (
            [qkv, onp.random.rand(32, 16, 16).astype("float32")],
            dict(heads=8)),
        "_contrib_quantize_v2": ([img], {}),
        "_contrib_dequantize": (
            [onp.random.randint(-127, 127, (16, 16)).astype("int8"),
             onp.array([-1.0], "float32"), onp.array([1.0], "float32")],
            {}),
        "one_hot": ([onp.arange(16, dtype="float32")], dict(depth=32)),
        "Embedding": ([onp.arange(16, dtype="float32"),
                       onp.random.rand(100, 32).astype("float32")],
                      dict(input_dim=100, output_dim=32)),
        "SequenceMask": ([seq], {}),
        "topk": ([onp.random.rand(16, 64).astype("float32")],
                 dict(k=4)),
        "pick": ([onp.random.rand(16, 8).astype("float32"),
                  onp.zeros(16, "float32")], {}),
        # ---- kwarg-required tail (r04: the grad sweep and opperf share
        # this table; every differentiable op needs a probeable spec)
        "_plus_scalar": ([img], dict(scalar=2.0)),
        "_minus_scalar": ([img], dict(scalar=2.0)),
        "_rminus_scalar": ([img], dict(scalar=2.0)),
        "_mul_scalar": ([img], dict(scalar=2.0)),
        "_div_scalar": ([img], dict(scalar=2.0)),
        "_power_scalar": ([img], dict(scalar=2.0)),
        # (_mod/_rmod/_rdiv/_rpower scalar variants live in the
        # FD-conditioned block below)
        "_maximum_scalar": ([img], dict(scalar=0.5)),
        "_minimum_scalar": ([img], dict(scalar=0.5)),
        "clip": ([img], dict(a_min=0.2, a_max=0.8)),
        "tile": ([onp.random.rand(8, 8).astype("float32")],
                 dict(reps=(2, 3))),
        "repeat": ([onp.random.rand(8, 8).astype("float32")],
                   dict(repeats=3)),
        "flip": ([onp.random.rand(8, 8).astype("float32")],
                 dict(axis=0)),
        "expand_dims": ([onp.random.rand(8, 8).astype("float32")],
                        dict(axis=1)),
        "slice": ([onp.random.rand(16, 16).astype("float32")],
                  dict(begin=(2, 2), end=(10, 12))),
        "slice_axis": ([onp.random.rand(16, 16).astype("float32")],
                       dict(axis=0, begin=2, end=10)),
        "broadcast_to": ([onp.random.rand(1, 16).astype("float32")],
                         dict(shape=(8, 16))),
        "broadcast_axes": ([onp.random.rand(1, 16).astype("float32")],
                           dict(axis=0, size=8)),
        "depth_to_space": ([onp.random.rand(2, 8, 4, 4)
                            .astype("float32")], dict(block_size=2)),
        "space_to_depth": ([onp.random.rand(2, 2, 8, 8)
                            .astype("float32")], dict(block_size=2)),
        "split_v2": ([onp.random.rand(8, 16).astype("float32")],
                     dict(indices=(2, 5), _num=3)),
        "gather_nd": ([onp.random.rand(8, 8).astype("float32"),
                       onp.array([[0, 2, 4], [1, 3, 5]], "int64")], {}),
        "scatter_nd": ([onp.random.rand(3).astype("float32"),
                        onp.array([[0, 2, 4]], "int64")],
                       dict(shape=(8,))),
        "batch_take": ([onp.random.rand(16, 16).astype("float32"),
                        onp.arange(16, dtype="int64")], {}),
        "take": ([onp.random.rand(32, 8).astype("float32"),
                  onp.arange(16, dtype="int64")], {}),
        "amp_cast": ([img], dict(dtype="float32")),
        "amp_multicast": ([img, img.copy()], dict(num_outputs=2)),
        "_contrib_dot_product_attention": (
            [onp.random.rand(2, 16, 32).astype("float32"),
             onp.random.rand(2, 16, 32).astype("float32"),
             onp.random.rand(2, 16, 32).astype("float32")],
            dict(num_heads=4, interpret=True)),
        "_random_pdf_uniform": (
            [onp.random.uniform(0.4, 0.6, (8, 16)).astype("float32"),
             onp.full((8,), 0.05, "float32"),
             onp.full((8,), 0.95, "float32")], {}),
        "_random_pdf_dirichlet": (
            [_simplex(8, 4), onp.random.uniform(1.5, 2.5, (8, 4))
             .astype("float32")], {}),
        # conditioned linalg inputs: random 128x128 determinants/
        # inverses are numerically meaningless for FD checks
        "_linalg_det": ([_spd(6)], {}),
        "_npi_det": ([_spd(6)], {}),
        "_linalg_potrf": ([_spd(6)], {}),
        "_npi_cholesky": ([_spd(6)], {}),
        "_linalg_potri": ([_spd(6)], {}),
        "_linalg_trsm": ([_tril(6), onp.random.rand(6, 6)
                          .astype("float32")], {}),
        "_npi_tensorinv": ([_spd(6).reshape(2, 3, 2, 3)], dict(ind=2)),
        "_npi_matrix_power": ([_spd(6)], dict(n=2)),
        "_npi_cross": ([onp.random.rand(8, 3).astype("float32"),
                        onp.random.rand(8, 3).astype("float32")], {}),
        "_npi_moveaxis": ([onp.random.rand(4, 6, 8).astype("float32")],
                          dict(source=0, destination=2)),
        "_npi_roll": ([onp.random.rand(8, 8).astype("float32")],
                      dict(shift=3, axis=1)),
        "_npi_rollaxis": ([onp.random.rand(4, 6, 8).astype("float32")],
                          dict(axis=2, start=0)),
        "_npi_take_along_axis": (
            [onp.random.rand(8, 8).astype("float32"),
             onp.random.randint(0, 8, (8, 4)).astype("int64")],
            dict(axis=1)),
        "_np_arccosh": ([onp.random.uniform(1.5, 3.0, (8, 16))
                         .astype("float32")], {}),
        "_hypot_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                           .astype("float32")], dict(scalar=2.0)),
        # denominators bounded away from numerator range: keeps the
        # fmod/floor family off its kink lattice for FD
        "_mod": ([onp.random.uniform(0.1, 0.4, (8, 16))
                  .astype("float32"),
                  onp.random.uniform(0.6, 0.9, (8, 16))
                  .astype("float32")], {}),
        "_npi_fmod": ([onp.random.uniform(0.1, 0.4, (8, 16))
                       .astype("float32"),
                       onp.random.uniform(0.6, 0.9, (8, 16))
                       .astype("float32")], {}),
        "_npi_floor_divide": ([onp.random.uniform(0.1, 0.4, (8, 16))
                               .astype("float32"),
                               onp.random.uniform(0.6, 0.9, (8, 16))
                               .astype("float32")], {}),
        "_mod_scalar": ([onp.random.uniform(0.1, 0.9, (8, 16))
                         .astype("float32")], dict(scalar=2.0)),
        "_rmod_scalar": ([onp.random.uniform(1.1, 1.9, (8, 16))
                          .astype("float32")], dict(scalar=1.0)),
        "_rdiv_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                          .astype("float32")], dict(scalar=2.0)),
        "_rpower_scalar": ([onp.random.uniform(0.3, 0.9, (8, 16))
                            .astype("float32")], dict(scalar=2.0)),
        "CTCLoss": ([onp.random.rand(10, 2, 6).astype("float32"),
                     onp.array([[1, 2, 3, 0], [2, 4, 0, 0]],
                               "float32")], {}),
        "BilinearSampler": (
            [onp.random.rand(2, 3, 8, 8).astype("float32"),
             onp.random.uniform(-0.9, 0.9, (2, 2, 8, 8))
             .astype("float32")], {}),
        "SpatialTransformer": (
            [onp.random.rand(2, 3, 8, 8).astype("float32"),
             onp.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]] * 2,
                       "float32")],
            dict(target_shape=(8, 8), transform_type="affine",
                 sampler_type="bilinear")),
        "_contrib_interleaved_matmul_encdec_qk": (
            [onp.random.rand(12, 2, 32).astype("float32"),
             onp.random.rand(10, 2, 64).astype("float32")],
            dict(heads=4)),
        "_contrib_interleaved_matmul_encdec_valatt": (
            [onp.random.rand(10, 2, 64).astype("float32"),
             onp.random.rand(8, 12, 10).astype("float32")],
            dict(heads=4)),
    }


def _spd(n):
    a = onp.random.RandomState(3).rand(n, n).astype("float32")
    m = a @ a.T + n * onp.eye(n, dtype="float32")
    # normalize so det ~ O(1): determinant-family FD otherwise sweeps
    # the loss's cos() through multiple periods per epsilon step
    return (m / n).astype("float32")


def _tril(n):
    a = onp.tril(onp.random.RandomState(4).rand(n, n)).astype("float32")
    return a + n * onp.eye(n, dtype="float32")


def _simplex(b, k):
    a = onp.random.RandomState(5).rand(b, k).astype("float32") + 0.2
    return a / a.sum(-1, keepdims=True)


def bench_op(opname, inputs, params, ctx, runs):
    """Marginal per-call device time via the chained fori_loop timer
    (benchmark/devtime.py).  Round 3's host-loop two-K sweep produced
    153 negative timings out of 370 rows — tunnel readback jitter
    swamped sub-ms ops; the device-side chain makes that impossible by
    construction (one program, one scalar readback, data-dependent
    iterations)."""
    import jax

    from devtime import device_chain_time

    op = get_op(opname)
    vals = [mx.nd.array(x, ctx=ctx)._data for x in inputs]
    kwargs = dict(params)
    if op.key_param and op.key_param not in kwargs:
        kwargs[op.key_param] = jax.random.key(0)

    def fn(*args):
        return op.fn(*args, **kwargs)

    dt, _ = device_chain_time(fn, vals, target_spread=0.4,
                              trials=max(3, min(runs // 8, 5)),
                              subtract_overhead=True)
    return dt


# ops whose signatures genuinely need bespoke shapes/params beyond the
# curated table and the auto-probe (IO-coupled, subgraph-attr, or
# index-typed inputs); everything else in the registry gets timed
SKIP_OPS = frozenset((
    "_foreach", "_while_loop", "_cond",  # subgraph-JSON attrs
    "_contrib_count_sketch",  # integer hash inputs
    "custom",  # user-provided op body
    # complex-valued iFFT is UNIMPLEMENTED on the axon TPU backend, and
    # a failed execution poisons the tunnel stream for every op after
    # it — keep it out of the sweep
    "_contrib_ifft",
))


def auto_inputs(opname):
    """Probe an input signature: square activations at several arities,
    with a per-family shape heuristic for common tensor+vector ops."""
    op = get_op(opname)
    x = onp.random.uniform(0.3, 0.9, (128, 128)).astype("float32")
    v = onp.random.uniform(0.3, 0.9, (128,)).astype("float32")
    candidates = [[x], [x, x], [x, x, x], [v], [v, v], [x, v]]
    for args in candidates:
        try:
            vals = [mx.nd.array(a)._data for a in args]
            kwargs = {}
            if op.key_param:
                import jax

                kwargs[op.key_param] = jax.random.key(0)
            out = op.fn(*vals, **kwargs)
            if isinstance(out, (tuple, list)) and len(out) == 0:
                return None
            return args, {}
        except Exception:
            continue
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma list; default = curated + all probe-able")
    ap.add_argument("--runs", type=int, default=25)
    ap.add_argument("--large", action="store_true")
    ap.add_argument("--baseline", default=None,
                    help="prior OPPERF jsonl; adds per-op regression "
                         "columns (prev_ms, speedup)")
    args = ap.parse_args()

    prev = {}
    if args.baseline:
        with open(args.baseline) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "op" in row and "avg_time_ms" in row:
                    prev[row["op"]] = row["avg_time_ms"]

    ctx = mx.gpu(0)
    curated = _standard_inputs(args.large)
    if args.ops:
        names = args.ops.split(",")
    else:
        # registry-wide (reference opperf runs every registered op):
        # curated shapes win, auto-probe covers the rest, SKIP_OPS
        # documents the ops needing bespoke harnesses
        seen_defs = {}
        for o in sorted(list_ops()):
            if o in SKIP_OPS:
                continue
            seen_defs.setdefault(id(get_op(o)), o)  # dedupe aliases
        names = sorted(set(list(curated) + list(seen_defs.values())))
    skipped = []
    for name in names:
        if name in curated:
            spec = curated[name]
        else:
            spec = auto_inputs(name)
            if spec is None:
                skipped.append(name)
                continue
        try:
            dt = bench_op(name, spec[0], spec[1], ctx, args.runs)
        except Exception as e:
            # auto-probed inputs legitimately miss some signatures, but
            # an explicitly requested op failing must be visible
            if args.ops:
                print(json.dumps({"op": name, "error": repr(e)}),
                      flush=True)
            else:
                skipped.append(name)
            continue
        row = {"op": name, "avg_time_ms": round(dt * 1e3, 4),
               "method": "device-chain"}
        if name in prev:
            row["prev_ms"] = prev[name]
            if prev[name] > 0 and dt > 0:
                row["speedup_vs_prev"] = round(prev[name] / (dt * 1e3), 2)
        print(json.dumps(row), flush=True)
    if skipped:
        print(json.dumps({"skipped_unprobeable": len(skipped),
                          "ops": skipped}), flush=True)


if __name__ == "__main__":
    main()
