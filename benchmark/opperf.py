#!/usr/bin/env python
"""Per-operator benchmark harness (reference: benchmark/opperf/ —
opperf.py runs every registered op with timing via the profiler).

Times eager dispatch+execution of registered ops on representative
shapes, emitting one JSON line per op:

    python benchmark/opperf.py [--ops dot,Convolution] [--warmup 5]
        [--runs 25] [--large]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.ops.registry import get_op, list_ops  # noqa: E402


def _standard_inputs(large=False):
    n = 1024 if large else 128
    a = onp.random.rand(n, n).astype("float32")
    return {
        # (inputs, params) per op family; unary/binary auto-probe below
        "dot": ([a, a], {}),
        "batch_dot": ([onp.random.rand(8, n, 64).astype("float32"),
                       onp.random.rand(8, 64, n).astype("float32")], {}),
        "FullyConnected": ([a, a, onp.zeros(n, "float32")],
                           dict(num_hidden=n)),
        "Convolution": ([onp.random.rand(8, 32, 64, 64).astype("float32"),
                         onp.random.rand(64, 32, 3, 3).astype("float32"),
                         onp.zeros(64, "float32")],
                        dict(kernel=(3, 3), num_filter=64, pad=(1, 1))),
        "Pooling": ([onp.random.rand(8, 32, 64, 64).astype("float32")],
                    dict(kernel=(2, 2), stride=(2, 2))),
        "BatchNorm": ([onp.random.rand(8, 32, 32, 32).astype("float32"),
                       onp.ones(32, "float32"), onp.zeros(32, "float32"),
                       onp.zeros(32, "float32"), onp.ones(32, "float32")],
                      {}),
        "softmax": ([a], {}),
        "sum": ([a], {}),
        "transpose": ([a], {}),
        "sort": ([a], {}),
        "_npi_einsum": ([a, a], dict(subscripts="ij,jk->ik")),
    }


def bench_op(opname, inputs, params, ctx, warmup, runs):
    nd_inputs = [mx.nd.array(x, ctx=ctx) for x in inputs]
    for _ in range(max(1, warmup)):  # >=1: compile before the clock
        out = mx.nd.invoke(opname, nd_inputs, **params)
    o = out[0] if isinstance(out, (list, tuple)) else out
    o.wait_to_read()
    t0 = time.perf_counter()
    for _ in range(runs):
        out = mx.nd.invoke(opname, nd_inputs, **params)
    o = out[0] if isinstance(out, (list, tuple)) else out
    o.wait_to_read()
    return (time.perf_counter() - t0) / runs


def auto_inputs(opname):
    op = get_op(opname)
    x = onp.random.uniform(0.3, 0.9, (128, 128)).astype("float32")
    for arity in (1, 2):
        try:
            args = [x] * arity
            out = op.fn(*[mx.nd.array(a)._data for a in args])
            if isinstance(out, (tuple, list)):
                return None
            return args, {}
        except Exception:
            continue
    return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", default=None,
                    help="comma list; default = curated + all probe-able")
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--runs", type=int, default=25)
    ap.add_argument("--large", action="store_true")
    args = ap.parse_args()

    ctx = mx.gpu(0)
    curated = _standard_inputs(args.large)
    if args.ops:
        names = args.ops.split(",")
    else:
        names = sorted(set(list(curated) + [
            o for o in list_ops()
            if not o.startswith("_") and get_op(o).key_param is None]))
    for name in names:
        if name in curated:
            spec = curated[name]
        else:
            spec = auto_inputs(name)
            if spec is None:
                continue
        try:
            dt = bench_op(name, spec[0], spec[1], ctx, args.warmup,
                          args.runs)
        except Exception as e:
            # auto-probed inputs legitimately miss some signatures, but
            # an explicitly requested op failing must be visible
            if args.ops:
                print(json.dumps({"op": name, "error": repr(e)}),
                      flush=True)
            continue
        print(json.dumps({"op": name, "avg_time_ms": round(dt * 1e3, 4),
                          "runs": args.runs}), flush=True)


if __name__ == "__main__":
    main()
