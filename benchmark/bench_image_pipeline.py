"""Image pipeline throughput benchmark — can the host feed the chip?

Builds a synthetic .rec of JPEG images, then measures ImageRecordIter
decode+augment throughput (reference: the C++ ImageRecordIter2 must
sustain the training rate; BENCH target >3,000 img/s of 224x224).

    python benchmark/bench_image_pipeline.py [--n 2048] [--threads N]
"""
from __future__ import annotations

import argparse
import io as _io
import json
import os
import sys
import tempfile
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from PIL import Image  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import recordio  # noqa: E402


def build_rec(path, n, h=256, w=256):
    rec = recordio.MXRecordIO(path, "w")
    rng = onp.random.RandomState(0)
    # a handful of distinct JPEGs re-referenced (decode cost dominates,
    # content doesn't matter)
    jpgs = []
    for _ in range(32):
        arr = (rng.rand(h, w, 3) * 255).astype("uint8")
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, "JPEG", quality=90)
        jpgs.append(buf.getvalue())
    for i in range(n):
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        rec.write(recordio.pack(header, jpgs[i % len(jpgs)]))
    rec.close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--threads", type=int, default=0,
                    help="0 = all cores")
    ap.add_argument("--batch", type=int, default=128)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "bench.rec")
        build_rec(rec, args.n)
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 224, 224),
            batch_size=args.batch, rand_crop=True, rand_mirror=True,
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.4, std_g=57.1, std_b=57.4,
            preprocess_threads=args.threads, prefetch_buffer=8)
        # warmup epoch
        for _ in it:
            pass
        it.reset()
        t0 = time.perf_counter()
        count = 0
        for b in it:
            count += b.data[0].shape[0] - b.pad
        dt = time.perf_counter() - t0
        it.close()
        print(json.dumps({
            "metric": "image_pipeline_throughput",
            "value": round(count / dt, 2), "unit": "img/s",
            "images": count, "threads": args.threads or "all",
        }))


if __name__ == "__main__":
    main()
