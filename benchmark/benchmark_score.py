"""Inference scoring benchmark — the reference's
example/image-classification/benchmark_score.py rebuilt for TPU.

Scores model-zoo networks (batched forward only, no grad) at several
batch sizes and dtypes, printing one JSON line per configuration:

    {"model": "resnet50_v1", "batch": 32, "dtype": "bfloat16",
     "throughput": ..., "unit": "img/s"}

Reference anchors (BASELINE.md): ResNet-50 fp32 1,076.81 img/s (bs 32)
and fp16 2,085.51 img/s on V100; ResNet-152 451.82 / 887.34.

Usage:  python benchmark/benchmark_score.py [--models resnet50_v1,...]
        [--batches 1,32,128] [--dtypes float32,bfloat16]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_ALLOWED_DTYPES = ("float32", "bfloat16", "float16")


def score_model(model_name, batches, dtypes,
                image_shape=(3, 224, 224)):
    import jax
    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import amp_cast_params, functionalize

    ctx = mx.gpu(0)  # falls back to cpu on accelerator-less hosts
    net = gluon.model_zoo.vision.get_model(model_name, classes=1000)
    net.initialize(init=mx.init.Xavier(), ctx=ctx)
    net(mx.nd.zeros((1,) + image_shape, ctx=ctx))
    params0, apply_fn = functionalize(net, train=False)

    # timing via the device-chained fori_loop (benchmark/devtime.py) —
    # the r03 host-loop K-sweep carried ~40 ms dispatch jitter, which
    # manufactured an apparent "throughput regresses with batch size"
    # (VERDICT r03 weak #4); the chained method measures each batch
    # size to ~1-2%.
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from devtime import device_chain_time

    for dtype in dtypes:
        cdtype = jnp.dtype(dtype)
        params = params0 if dtype == "float32" \
            else amp_cast_params(params0, cdtype)
        for batch in batches:
            x = jnp.asarray(onp.random.rand(batch, *image_shape),
                            dtype=cdtype)
            dt, _ = device_chain_time(
                lambda xv, p: apply_fn(p, xv), [x, params],
                target_spread=0.5)
            yield {"model": model_name, "batch": batch, "dtype": dtype,
                   "throughput": round(batch / dt, 2),
                   "ms_per_batch": round(dt * 1e3, 3),
                   "unit": "img/s"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", default="resnet50_v1,resnet152_v1")
    ap.add_argument("--batches", default="1,32,128")
    ap.add_argument("--dtypes", default="float32,bfloat16")
    args = ap.parse_args()
    dtypes = args.dtypes.split(",")
    unknown = set(dtypes) - set(_ALLOWED_DTYPES)
    if unknown:
        ap.error(f"unknown dtypes: {sorted(unknown)} "
                 f"(choose from {sorted(_ALLOWED_DTYPES)})")
    batches = [int(b) for b in args.batches.split(",")]
    for model in args.models.split(","):
        for row in score_model(model, batches, dtypes):
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
